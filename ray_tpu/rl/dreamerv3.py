"""DreamerV3: world-model RL (RSSM + imagination actor-critic) in jax.

Reference analog: rllib/algorithms/dreamerv3/ (tf; world_model.py RSSM with
categorical latents, actor/critic trained on imagined trajectories). TPU-
native redesign: the whole update — RSSM rollout over the sequence batch,
world-model losses, imagination rollout, actor-critic losses, both grad
steps — is ONE jit-compiled function built from lax.scan, so XLA fuses the
recurrence instead of dispatching per timestep.

Kept from the DreamerV3 recipe (scaled to vector-obs toy envs):
  * categorical latents (classes x cats) with straight-through gradients
    and 1% unimix smoothing
  * KL balancing: dyn loss KL(sg(post)||prior) + 0.1 * rep loss
    KL(post||sg(prior)), both with free bits (1 nat)
  * symlog regression for decoder & reward; continue head
  * lambda-returns in imagination; percentile return normalization
    (S = EMA of P95-P5) scaling the actor's advantages
  * EMA critic target regularizing the critic toward its own EMA
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class DreamerV3Config:
    env: str = "CartPole-v1"
    obs_dim: int = 4
    n_actions: int = 2
    deter: int = 128            # GRU/deterministic state
    classes: int = 8            # categorical latent: classes x cats
    cats: int = 8
    hidden: int = 128
    batch_size: int = 16        # sequences per update
    seq_len: int = 32
    horizon: int = 10           # imagination length
    lr_model: float = 1e-3
    lr_actor: float = 1e-3
    lr_critic: float = 1e-3
    gamma: float = 0.985
    lam: float = 0.95
    entropy: float = 3e-3
    free_nats: float = 1.0
    beta_dyn: float = 1.0
    beta_rep: float = 0.1
    unimix: float = 0.01
    critic_ema_decay: float = 0.98
    critic_ema_reg: float = 1.0
    replay_capacity: int = 100_000
    learning_starts: int = 1_000
    envs: int = 8
    rollout_length: int = 64
    updates_per_iteration: int = 8

    @property
    def stoch(self) -> int:
        return self.classes * self.cats


# ------------------------------------------------------------- numerics

def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _linear(key, n_in, n_out):
    w = jax.random.normal(key, (n_in, n_out)) * np.sqrt(1.0 / n_in)
    return {"w": w, "b": jnp.zeros(n_out)}


def _mlp(key, n_in, hidden, n_out):
    k1, k2 = jax.random.split(key)
    return [_linear(k1, n_in, hidden), _linear(k2, hidden, n_out)]


def _mlp_fwd(layers, x):
    x = jnp.tanh(x @ layers[0]["w"] + layers[0]["b"])
    return x @ layers[1]["w"] + layers[1]["b"]


# ----------------------------------------------------------------- RSSM

def init_world_model(config: DreamerV3Config, key) -> Dict:
    ks = jax.random.split(key, 8)
    d, s, h = config.deter, config.stoch, config.hidden
    return {
        "enc": _mlp(ks[0], config.obs_dim, h, h),
        # GRU over [z, a]: one fused kernel producing r/u/c gates.
        "gru": _linear(ks[1], d + s + config.n_actions, 3 * d),
        "prior": _mlp(ks[2], d, h, s),
        "post": _mlp(ks[3], d + h, h, s),
        "dec": _mlp(ks[4], d + s, h, config.obs_dim),
        "rew": _mlp(ks[5], d + s, h, 1),
        "cont": _mlp(ks[6], d + s, h, 1),
    }


def init_actor_critic(config: DreamerV3Config, key) -> Tuple[Dict, Dict]:
    k1, k2 = jax.random.split(key)
    feat = config.deter + config.stoch
    actor = {"net": _mlp(k1, feat, config.hidden, config.n_actions)}
    critic = {"net": _mlp(k2, feat, config.hidden, 1)}
    return actor, critic


def _gru_step(params, h, x):
    gates = jnp.concatenate([h, x], -1) @ params["gru"]["w"] + params["gru"]["b"]
    r, u, c = jnp.split(gates, 3, -1)
    r, u = jax.nn.sigmoid(r), jax.nn.sigmoid(u)
    c = jnp.tanh(r * c)
    return u * c + (1 - u) * h


def _unimix_logits(logits, config):
    """1% uniform mixture keeps every class reachable (v3 trick)."""
    B = logits.shape[:-1]
    lg = logits.reshape(*B, config.classes, config.cats)
    probs = jax.nn.softmax(lg, -1)
    probs = (1 - config.unimix) * probs + config.unimix / config.cats
    return jnp.log(probs).reshape(*B, config.stoch)


def _sample_latent(key, logits, config):
    """Straight-through categorical sample, flattened one-hots."""
    B = logits.shape[:-1]
    lg = logits.reshape(*B, config.classes, config.cats)
    idx = jax.random.categorical(key, lg, -1)
    onehot = jax.nn.one_hot(idx, config.cats, dtype=lg.dtype)
    probs = jax.nn.softmax(lg, -1)
    st = onehot + probs - jax.lax.stop_gradient(probs)  # straight-through
    return st.reshape(*B, config.stoch)


def _kl(lhs_logits, rhs_logits, config):
    """sum over classes of KL(Cat(lhs) || Cat(rhs)); logits pre-unimix."""
    B = lhs_logits.shape[:-1]
    l = lhs_logits.reshape(*B, config.classes, config.cats)
    r = rhs_logits.reshape(*B, config.classes, config.cats)
    lp = jax.nn.log_softmax(l, -1)
    rp = jax.nn.log_softmax(r, -1)
    return (jnp.exp(lp) * (lp - rp)).sum(-1).sum(-1)


def observe_sequence(params, config: DreamerV3Config, obs, actions, is_first,
                     key):
    """Run the RSSM over a [B, T, ...] batch; returns posterior features
    [B, T, deter+stoch] and the prior/posterior logits for the KL losses.
    is_first masks the recurrent state to zero at episode starts."""
    B = obs.shape[0]
    embed = _mlp_fwd(params["enc"], symlog(obs))          # [B,T,h]
    a_onehot = jax.nn.one_hot(actions, config.n_actions)

    def step(carry, inp):
        h, z, k = carry
        em, a_prev, first = inp
        mask = (1.0 - first)[:, None]
        h = h * mask
        z = z * mask
        a_prev = a_prev * mask
        h = _gru_step(params, h, jnp.concatenate([z, a_prev], -1))
        prior_lg = _unimix_logits(_mlp_fwd(params["prior"], h), config)
        post_lg = _unimix_logits(
            _mlp_fwd(params["post"], jnp.concatenate([h, em], -1)), config)
        k, sub = jax.random.split(k)
        z = _sample_latent(sub, post_lg, config)
        return (h, z, k), (h, z, prior_lg, post_lg)

    h0 = jnp.zeros((B, config.deter))
    z0 = jnp.zeros((B, config.stoch))
    # Scan over time: inputs are [T, B, ...].
    a_prev = jnp.concatenate([jnp.zeros_like(a_onehot[:, :1]),
                              a_onehot[:, :-1]], 1)
    inputs = (embed.transpose(1, 0, 2), a_prev.transpose(1, 0, 2),
              is_first.transpose(1, 0))
    (_, _, _), (hs, zs, prior_lg, post_lg) = jax.lax.scan(
        step, (h0, z0, key), inputs)
    feat = jnp.concatenate([hs, zs], -1).transpose(1, 0, 2)  # [B,T,f]
    return feat, prior_lg.transpose(1, 0, 2), post_lg.transpose(1, 0, 2), \
        hs.transpose(1, 0, 2), zs.transpose(1, 0, 2)


def world_model_loss(params, config: DreamerV3Config, batch, key):
    feat, prior_lg, post_lg, hs, zs = observe_sequence(
        params, config, batch["obs"], batch["actions"], batch["is_first"],
        key)
    dec = _mlp_fwd(params["dec"], feat)
    rew = _mlp_fwd(params["rew"], feat)[..., 0]
    cont = _mlp_fwd(params["cont"], feat)[..., 0]
    pred_loss = (
        ((dec - symlog(batch["obs"])) ** 2).sum(-1)
        + (rew - symlog(batch["rewards"])) ** 2
        + jnp.maximum(0.0, -jax.nn.log_sigmoid(
            jnp.where(batch["continues"] > 0.5, cont, -cont)))
    )
    dyn = jnp.maximum(config.free_nats,
                      _kl(jax.lax.stop_gradient(post_lg), prior_lg, config))
    rep = jnp.maximum(config.free_nats,
                      _kl(post_lg, jax.lax.stop_gradient(prior_lg), config))
    loss = (pred_loss + config.beta_dyn * dyn + config.beta_rep * rep).mean()
    return loss, (feat, hs, zs)


# ----------------------------------------------------------- imagination

def imagine(params, actor, config: DreamerV3Config, h0, z0, key):
    """Roll the PRIOR forward under the policy from flattened posterior
    states. Returns features/actions/logps/entropies [H, N, ...]."""

    def step(carry, _):
        h, z, k = carry
        feat = jnp.concatenate([h, z], -1)
        logits = _mlp_fwd(actor["net"], feat)
        k, ka, kz = jax.random.split(k, 3)
        a = jax.random.categorical(ka, logits, -1)
        logp = jax.nn.log_softmax(logits, -1)
        ent = -(jnp.exp(logp) * logp).sum(-1)
        a_onehot = jax.nn.one_hot(a, config.n_actions)
        h = _gru_step(params, h, jnp.concatenate([z, a_onehot], -1))
        prior_lg = _unimix_logits(_mlp_fwd(params["prior"], h), config)
        z = _sample_latent(kz, prior_lg, config)
        chosen_logp = jnp.take_along_axis(logp, a[:, None], -1)[:, 0]
        return (h, z, k), (feat, a, chosen_logp, ent)

    (_, _, _), (feats, acts, logps, ents) = jax.lax.scan(
        step, (h0, z0, key), None, length=config.horizon)
    return feats, acts, logps, ents


def lambda_returns(rewards, values, continues, bootstrap, gamma, lam):
    """Standard TD(lambda) returns computed backwards with lax.scan."""

    def step(next_ret, inp):
        r, v_next, c = inp
        ret = r + gamma * c * ((1 - lam) * v_next + lam * next_ret)
        return ret, ret

    inputs = (rewards, values, continues)
    _, rets = jax.lax.scan(step, bootstrap, inputs, reverse=True)
    return rets


# ------------------------------------------------------------ the update

def make_update_fn(config: DreamerV3Config, model_opt, actor_opt, critic_opt):
    import optax

    def update(state, batch, key):
        kw, ki, kc = jax.random.split(key, 3)

        # --- world model ---------------------------------------------
        (wm_loss, (feat, hs, zs)), wm_grads = jax.value_and_grad(
            world_model_loss, has_aux=True)(
                state["model"], config, batch, kw)
        updates, mo = model_opt.update(wm_grads, state["model_opt"],
                                       state["model"])
        model = optax.apply_updates(state["model"], updates)

        # --- imagination --------------------------------------------
        # Start states: every posterior state, flattened, grads cut.
        h0 = jax.lax.stop_gradient(hs.reshape(-1, config.deter))
        z0 = jax.lax.stop_gradient(zs.reshape(-1, config.stoch))

        def ac_losses(ac):
            """One imagination rollout; joint grads are clean because no
            gradient path crosses actor<->critic (actions are categorical
            samples, advantages are stop_gradient'd)."""
            actor, critic = ac["actor"], ac["critic"]
            feats, acts, logps, ents = imagine(
                model, actor, config, h0, z0, ki)
            # feats[t] = s_t; transition s_t -a_t-> s_{t+1} earns the
            # reward/continue predicted AT s_{t+1}.
            rew = symexp(_mlp_fwd(model["rew"], feats)[..., 0])[1:]
            cont = jax.nn.sigmoid(
                _mlp_fwd(model["cont"], feats)[..., 0])[1:]
            values = symexp(_mlp_fwd(critic["net"], feats)[..., 0])
            rets = lambda_returns(rew, values[1:], cont,
                                  values[-1], config.gamma, config.lam)
            rets = jax.lax.stop_gradient(rets)   # [H-1]
            # Percentile normalization of advantages (v3): scale by
            # EMA(P95 - P5) of returns, floored at 1.
            scale = jnp.maximum(1.0, state["ret_scale"])
            adv = (rets - values[:-1]) / scale
            actor_loss = (-jax.lax.stop_gradient(adv) * logps[:-1]
                          - config.entropy * ents[:-1]).mean()
            critic_pred = _mlp_fwd(critic["net"], feats)[..., 0][:-1]
            ema_pred = jax.lax.stop_gradient(
                _mlp_fwd(state["critic_ema"]["net"], feats)[..., 0][:-1])
            critic_loss = ((critic_pred - symlog(rets)) ** 2).mean() \
                + config.critic_ema_reg * ((critic_pred - ema_pred) ** 2
                                           ).mean()
            p5, p95 = jnp.percentile(rets, jnp.array([5.0, 95.0]))
            return actor_loss + critic_loss, (actor_loss, critic_loss,
                                              p95 - p5, rets.mean())

        (_, aux), ac_grads = jax.value_and_grad(ac_losses, has_aux=True)(
            {"actor": state["actor"], "critic": state["critic"]})
        a_up, ao = actor_opt.update(ac_grads["actor"], state["actor_opt"],
                                    state["actor"])
        actor = optax.apply_updates(state["actor"], a_up)
        c_up, co = critic_opt.update(ac_grads["critic"], state["critic_opt"],
                                     state["critic"])
        critic = optax.apply_updates(state["critic"], c_up)
        ema = jax.tree_util.tree_map(
            lambda e, c: config.critic_ema_decay * e
            + (1 - config.critic_ema_decay) * c,
            state["critic_ema"], critic)
        ret_scale = 0.99 * state["ret_scale"] + 0.01 * aux[2]
        new_state = {
            "model": model, "model_opt": mo,
            "actor": actor, "actor_opt": ao,
            "critic": critic, "critic_opt": co, "critic_ema": ema,
            "ret_scale": ret_scale,
        }
        metrics = {"wm_loss": wm_loss, "actor_loss": aux[0],
                   "critic_loss": aux[1], "imag_return": aux[3]}
        return new_state, metrics

    return jax.jit(update)


# ------------------------------------------------------------- algorithm

class DreamerV3:
    """Collect with the latent policy; train world model + actor-critic.

    Single-learner layout (the toy-env regime): vectorized envs in-process,
    sequence replay, jit update. Scales the same way the other algorithms
    do (EnvRunner actors) once envs are remote-worthy."""

    def __init__(self, config: DreamerV3Config, seed: int = 0):
        import optax

        from ray_tpu.rl.env import make_env

        self.config = config
        self.env = make_env(config.env, config.envs, seed)
        self.obs = self.env.reset()
        key = jax.random.key(seed)
        k1, k2, k3 = jax.random.split(key, 3)
        model = init_world_model(config, k1)
        actor, critic = init_actor_critic(config, k2)
        model_opt = optax.adam(config.lr_model)
        actor_opt = optax.adam(config.lr_actor)
        critic_opt = optax.adam(config.lr_critic)
        self.state = {
            "model": model, "model_opt": model_opt.init(model),
            "actor": actor, "actor_opt": actor_opt.init(actor),
            "critic": critic, "critic_opt": critic_opt.init(critic),
            "critic_ema": jax.tree_util.tree_map(jnp.copy, critic),
            "ret_scale": jnp.asarray(1.0),
        }
        self.update_fn = make_update_fn(config, model_opt, actor_opt,
                                        critic_opt)
        self.key = k3
        self._act_fn = jax.jit(self._act)
        # Recurrent acting state per env.
        self._h = jnp.zeros((config.envs, config.deter))
        self._z = jnp.zeros((config.envs, config.stoch))
        self._prev_a = np.zeros(config.envs, dtype=np.int64)
        self._first = np.ones(config.envs, dtype=np.float32)
        # Sequence replay: contiguous per-env streams, sampled as windows.
        cap = config.replay_capacity // config.envs
        self._streams = {
            "obs": np.zeros((config.envs, cap, config.obs_dim), np.float32),
            "actions": np.zeros((config.envs, cap), np.int64),
            "rewards": np.zeros((config.envs, cap), np.float32),
            "continues": np.ones((config.envs, cap), np.float32),
            "is_first": np.zeros((config.envs, cap), np.float32),
        }
        self._cap = cap
        self._pos = 0
        self._full = False
        self.episode_returns: List[float] = []
        self._running = np.zeros(config.envs)
        self.iteration = 0
        self.rng = np.random.default_rng(seed)

    # -- acting ------------------------------------------------------------
    def _act(self, model, actor, h, z, obs, prev_a, is_first, key):
        config = self.config
        mask = (1.0 - is_first)[:, None]
        h = h * mask
        z = z * mask
        a_onehot = jax.nn.one_hot(prev_a, config.n_actions) * mask
        em = _mlp_fwd(model["enc"], symlog(obs))
        h = _gru_step(model, h, jnp.concatenate([z, a_onehot], -1))
        post_lg = _unimix_logits(
            _mlp_fwd(model["post"], jnp.concatenate([h, em], -1)), config)
        kz, ka = jax.random.split(key)
        z = _sample_latent(kz, post_lg, config)
        logits = _mlp_fwd(actor["net"], jnp.concatenate([h, z], -1))
        a = jax.random.categorical(ka, logits, -1)
        return h, z, a

    def _collect(self, steps: int):
        config = self.config
        for _ in range(steps):
            self.key, sub = jax.random.split(self.key)
            # Only model+actor ship to the jit (the full train state would
            # drag critic + optimizer trees through dispatch every step).
            h, z, a = self._act_fn(self.state["model"], self.state["actor"],
                                   self._h, self._z,
                                   jnp.asarray(self.obs),
                                   jnp.asarray(self._prev_a),
                                   jnp.asarray(self._first), sub)
            actions = np.asarray(a)
            obs_now = self.obs
            first_now = self._first.copy()
            next_obs, reward, done = self.env.step(actions)
            i = self._pos % self._cap
            self._streams["obs"][:, i] = obs_now
            self._streams["actions"][:, i] = actions
            self._streams["rewards"][:, i] = reward
            self._streams["continues"][:, i] = 1.0 - done
            self._streams["is_first"][:, i] = first_now
            self._pos += 1
            if self._pos >= self._cap:
                self._full = True
            self._h, self._z = h, z
            self._prev_a = actions
            self._first = done.astype(np.float32)
            self._running += reward
            for j in np.where(done)[0]:
                self.episode_returns.append(float(self._running[j]))
                self._running[j] = 0.0
            self.obs = self.env.current_obs()

    def _sample_batch(self) -> Dict[str, np.ndarray]:
        config = self.config
        hi = (self._cap if self._full else self._pos) - config.seq_len
        out = {k: [] for k in self._streams}
        seam = self._pos % self._cap  # oldest data starts here once full
        for _ in range(config.batch_size):
            e = self.rng.integers(0, config.envs)
            for _try in range(10):
                s = self.rng.integers(0, max(1, hi))
                # A window straddling the write seam would splice the
                # newest transitions onto the oldest.
                if not (self._full and s < seam < s + config.seq_len):
                    break
            for k, stream in self._streams.items():
                out[k].append(stream[e, s:s + config.seq_len])
        batch = {k: np.stack(v) for k, v in out.items()}
        # The window start acts as a sequence boundary for the RSSM.
        batch["is_first"][:, 0] = 1.0
        return batch

    def train(self) -> Dict:
        config = self.config
        self._collect(config.rollout_length)
        metrics = {}
        have = (self._cap if self._full else self._pos) * config.envs
        if have >= config.learning_starts:
            for _ in range(config.updates_per_iteration):
                self.key, sub = jax.random.split(self.key)
                batch = {k: jnp.asarray(v)
                         for k, v in self._sample_batch().items()}
                self.state, metrics = self.update_fn(self.state, batch, sub)
        self.iteration += 1
        recent = self.episode_returns[-20:]
        return {
            "iteration": self.iteration,
            "episode_return_mean": float(np.mean(recent)) if recent else 0.0,
            "episodes_total": len(self.episode_returns),
            "env_steps_total": self._pos * config.envs,
            **{k: float(v) for k, v in metrics.items()},
        }
