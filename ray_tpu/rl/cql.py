"""CQL: Conservative Q-Learning for offline RL (discrete actions).

Reference analog: rllib/algorithms/cql/ (CQL over SAC for continuous
control; the discrete form regularizes a DQN-style critic). TPU-native
shape: the whole update — double-DQN TD target, the CQL(H) conservative
regularizer, grad step, polyak target sync — is one jit-compiled function
over stacked offline batches, sharing the Q-network with rl/dqn.py.

CQL(H) for discrete actions adds to the TD loss:

    alpha * E_s[ logsumexp_a Q(s, a) - Q(s, a_data) ]

which pushes down Q-values for out-of-distribution actions while keeping
the dataset's actions competitive — the standard fix for the offline
over-estimation failure mode plain DQN exhibits on a fixed dataset.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.dqn import (
    DQNConfig,
    double_dqn_target,
    huber,
    init_q_network,
    q_forward,
)
from ray_tpu.rl.offline import iterate_minibatches, read_episodes


@dataclasses.dataclass(frozen=True)
class CQLConfig:
    obs_dim: int = 4
    n_actions: int = 2
    hidden: Tuple[int, ...] = (64, 64)
    gamma: float = 0.99
    lr: float = 1e-3
    alpha: float = 1.0            # conservative-regularizer weight
    target_update_tau: float = 0.01
    batch_size: int = 256
    epochs: int = 5

    def _dqn(self) -> DQNConfig:
        return DQNConfig(obs_dim=self.obs_dim, n_actions=self.n_actions,
                         hidden=self.hidden)


def cql_loss(params, target_params, batch, config: CQLConfig):
    q = q_forward(params, batch["obs"])
    q_taken = jnp.take_along_axis(
        q, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
    # Double-DQN target from the fixed dataset transitions (shared with
    # the online learner, rl/dqn.py).
    td = q_taken - double_dqn_target(params, target_params, batch,
                                     config.gamma)
    bellman = jnp.mean(huber(td))
    # CQL(H): minimize soft-max over all actions, maximize the data action.
    conservative = jnp.mean(jax.nn.logsumexp(q, axis=1) - q_taken)
    total = bellman + config.alpha * conservative
    return total, {"bellman_loss": bellman, "cql_loss": conservative}


def make_cql_update(config: CQLConfig, optimizer):
    @jax.jit
    def update(params, target_params, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(
            cql_loss, has_aux=True)(params, target_params, batch, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        tau = config.target_update_tau
        target_params = jax.tree.map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, params)
        return params, target_params, opt_state, {"loss": loss, **aux}

    return update


class CQL:
    """Offline trainer: conservative Q-learning from stored episodes.

    Requires shards with {obs, actions, rewards, dones, next_obs}
    (collect_episodes writes all five)."""

    def __init__(self, config: CQLConfig, data_path: str, seed: int = 0):
        self.config = config
        data = read_episodes(data_path)
        if "next_obs" not in data:
            raise ValueError(
                "CQL needs next_obs in the offline dataset; re-collect with "
                "a writer that stores transitions, not just observations")
        self.batch = {
            "obs": data["obs"].astype(np.float32),
            "actions": data["actions"].astype(np.int32),
            "rewards": data["rewards"].astype(np.float32),
            "dones": data["dones"].astype(np.float32),
            "next_obs": data["next_obs"].astype(np.float32),
        }
        self.params = init_q_network(config._dqn(), jax.random.key(seed))
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.update = make_cql_update(config, self.optimizer)
        self.rng = np.random.default_rng(seed)
        self.iteration = 0

    def train(self) -> Dict:
        metrics: Dict = {}
        for mb in iterate_minibatches(self.rng, self.batch,
                                      self.config.batch_size,
                                      self.config.epochs):
            self.params, self.target_params, self.opt_state, metrics = \
                self.update(self.params, self.target_params,
                            self.opt_state, mb)
        self.iteration += 1
        return {"training_iteration": self.iteration,
                **{k: float(v) for k, v in metrics.items()}}

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(q_forward(self.params, jnp.asarray(obs)))

    def greedy_actions(self, obs: np.ndarray) -> np.ndarray:
        return self.q_values(obs).argmax(-1)
