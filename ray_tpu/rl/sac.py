"""SAC (discrete-action variant): twin critics, stochastic actor, learned
temperature — one jit-compiled update.

Reference analog: rllib/algorithms/sac/ (SAC + SACTorchLearner); discrete
SAC follows Christodoulou 2019 (soft policy iteration with categorical
policies), which shares env plumbing with the other discrete-action
algorithms here and needs no reparameterized sampling on the update path —
everything reduces to dense matmuls on the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class SACConfig:
    env: str = "CartPole-v1"
    obs_dim: int = 4
    n_actions: int = 2
    hidden: Tuple[int, ...] = (64, 64)
    gamma: float = 0.99
    lr: float = 3e-4
    buffer_capacity: int = 50_000
    learning_starts: int = 500
    train_batch_size: int = 64
    tau: float = 0.01
    target_entropy_scale: float = 0.7    # target = scale * log(n_actions)
    rollout_length: int = 64
    num_env_runners: int = 2
    envs_per_runner: int = 4
    updates_per_iteration: int = 16


def _mlp_init(sizes, key, out_scale=1.0):
    keys = jax.random.split(key, len(sizes))
    layers = []
    for i in range(len(sizes) - 1):
        scale = out_scale if i == len(sizes) - 2 else np.sqrt(2.0 / sizes[i])
        w = jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * scale
        layers.append({"w": w, "b": jnp.zeros(sizes[i + 1])})
    return {"layers": layers}


def _mlp_forward(params, x):
    for layer in params["layers"][:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    last = params["layers"][-1]
    return x @ last["w"] + last["b"]


def init_sac(config: SACConfig, key) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    sizes = (config.obs_dim,) + config.hidden + (config.n_actions,)
    return {
        "actor": _mlp_init(sizes, k1, out_scale=0.01),
        "q1": _mlp_init(sizes, k2),
        "q2": _mlp_init(sizes, k3),
        "log_alpha": jnp.asarray(0.0),
    }


def actor_logits(params, obs):
    return _mlp_forward(params["actor"], obs)


def make_update_fn(config: SACConfig, optimizer):
    target_entropy = config.target_entropy_scale * np.log(config.n_actions)

    def losses(params, target_params, batch):
        logits = actor_logits(params, batch["obs"])
        logp = jax.nn.log_softmax(logits)
        probs = jnp.exp(logp)
        alpha = jnp.exp(params["log_alpha"])

        # Critic targets: soft state value of next state under current policy.
        next_logits = actor_logits(params, batch["next_obs"])
        next_logp = jax.nn.log_softmax(next_logits)
        next_probs = jnp.exp(next_logp)
        nq1 = _mlp_forward(target_params["q1"], batch["next_obs"])
        nq2 = _mlp_forward(target_params["q2"], batch["next_obs"])
        next_v = (next_probs * (jnp.minimum(nq1, nq2)
                                - alpha * next_logp)).sum(-1)
        target_q = batch["rewards"] + config.gamma * \
            (1.0 - batch["dones"]) * jax.lax.stop_gradient(next_v)

        q1 = _mlp_forward(params["q1"], batch["obs"])
        q2 = _mlp_forward(params["q2"], batch["obs"])
        a = batch["actions"][:, None]
        q1_taken = jnp.take_along_axis(q1, a, axis=1)[:, 0]
        q2_taken = jnp.take_along_axis(q2, a, axis=1)[:, 0]
        critic_loss = ((q1_taken - target_q) ** 2 +
                       (q2_taken - target_q) ** 2).mean()

        # Actor: maximize soft value under min-critic.
        min_q = jax.lax.stop_gradient(jnp.minimum(q1, q2))
        actor_loss = (probs * (jax.lax.stop_gradient(alpha) * logp
                               - min_q)).sum(-1).mean()

        # Temperature: match target entropy.
        entropy = -(probs * logp).sum(-1)
        alpha_loss = (params["log_alpha"] *
                      jax.lax.stop_gradient(entropy - target_entropy)).mean()
        total = critic_loss + actor_loss + alpha_loss
        return total, {"critic_loss": critic_loss, "actor_loss": actor_loss,
                       "alpha": alpha, "entropy": entropy.mean()}

    @jax.jit
    def update(params, target_params, opt_state, batch):
        import optax

        (_, metrics), grads = jax.value_and_grad(
            losses, has_aux=True)(params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        target_params = {
            k: jax.tree.map(
                lambda t, p: (1 - config.tau) * t + config.tau * p,
                target_params[k], params[k])
            for k in ("q1", "q2")}
        return params, target_params, opt_state, metrics

    return update


class SACRunner:
    """Actor: samples from the categorical policy (no epsilon schedule —
    exploration comes from entropy regularization)."""

    def __init__(self, config: SACConfig, seed: int):
        from ray_tpu.rl.env import make_env

        self.config = config
        self.env = make_env(config.env, config.envs_per_runner, seed)
        self.obs = self.env.reset()
        self.forward = jax.jit(actor_logits)
        self.rng = np.random.default_rng(seed)
        self.episode_returns = []
        self._running = np.zeros(config.envs_per_runner)

    def rollout(self, params) -> Dict[str, np.ndarray]:
        obs_b, act_b, rew_b, done_b, next_b = [], [], [], [], []
        for _ in range(self.config.rollout_length):
            logits = np.asarray(self.forward(params, jnp.asarray(self.obs)))
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            actions = np.array([self.rng.choice(len(p), p=p) for p in probs])
            next_obs, reward, done = self.env.step(actions)
            obs_b.append(self.obs); act_b.append(actions)
            rew_b.append(reward); done_b.append(done.astype(np.float32))
            next_b.append(next_obs)
            self._running += reward
            for i in np.where(done)[0]:
                self.episode_returns.append(float(self._running[i]))
                self._running[i] = 0.0
            # next_obs keeps terminal rows (the true s'); act next on
            # the post-auto-reset state or boundary transitions corrupt.
            self.obs = self.env.current_obs()
        return {
            "obs": np.concatenate(obs_b).astype(np.float32),
            "actions": np.concatenate(act_b).astype(np.int32),
            "rewards": np.concatenate(rew_b).astype(np.float32),
            "dones": np.concatenate(done_b).astype(np.float32),
            "next_obs": np.concatenate(next_b).astype(np.float32),
            "episode_returns": self.episode_returns[-50:],
        }


class SAC:
    def __init__(self, config: SACConfig):
        import optax

        import ray_tpu
        from ray_tpu.rl.replay_buffer import ReplayBuffer

        self.config = config
        self.params = init_sac(config, jax.random.key(0))
        self.target_params = {"q1": jax.tree.map(jnp.copy, self.params["q1"]),
                              "q2": jax.tree.map(jnp.copy, self.params["q2"])}
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.update_fn = make_update_fn(config, self.optimizer)
        self.buffer = ReplayBuffer(config.buffer_capacity)
        Runner = ray_tpu.remote(SACRunner)
        self.runners = [Runner.remote(config, seed=i)
                        for i in range(config.num_env_runners)]
        self.env_steps = 0
        self.iteration = 0

    def train(self) -> Dict:
        import time

        import ray_tpu

        t0 = time.perf_counter()
        params_host = jax.tree.map(np.asarray, self.params)
        refs = [r.rollout.remote(params_host) for r in self.runners]
        episode_returns = []
        for ref in refs:
            roll = ray_tpu.get(ref, timeout=300)
            episode_returns.extend(roll.pop("episode_returns"))
            self.env_steps += len(roll["obs"])
            self.buffer.add_batch(roll)
        metrics_acc = {}
        if len(self.buffer) >= self.config.learning_starts:
            for _ in range(self.config.updates_per_iteration):
                batch = {k: jnp.asarray(v) for k, v in
                         self.buffer.sample(self.config.train_batch_size).items()}
                self.params, self.target_params, self.opt_state, metrics = \
                    self.update_fn(self.params, self.target_params,
                                   self.opt_state, batch)
                metrics_acc = {k: float(v) for k, v in metrics.items()}
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "num_env_steps": self.env_steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **metrics_acc,
        }

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
