"""TD3: twin-delayed deterministic policy gradient (continuous control).

Reference analog: rllib/algorithms/td3/ (TD3 = DDPG + the three Fujimoto
2018 fixes). One jit-compiled update applies all three:

  * TWIN critics — the target is min(Q1', Q2'), curbing overestimation;
  * TARGET POLICY SMOOTHING — clipped gaussian noise on the target
    action regularizes the critic against sharp action-value spikes;
  * DELAYED actor + target updates — the actor (and polyak targets) move
    every `policy_delay` critic steps, under lax.cond so the whole
    update stays one compiled program (no data-dependent Python).

Rollouts add exploration noise to the deterministic tanh actor; the env
plumbing (vectorized runners as actors, replay buffer, train() metrics)
matches the other off-policy algorithms here (sac.py/dqn.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class TD3Config:
    env: str = "Pendulum-v1"
    obs_dim: int = 3
    action_dim: int = 1
    max_action: float = 2.0
    hidden: Tuple[int, ...] = (64, 64)
    gamma: float = 0.99
    lr: float = 1e-3
    buffer_capacity: int = 100_000
    learning_starts: int = 500
    train_batch_size: int = 128
    tau: float = 0.005
    exploration_noise: float = 0.2       # rollout-time gaussian (pre-clip)
    target_noise: float = 0.2            # target policy smoothing sigma
    target_noise_clip: float = 0.5
    policy_delay: int = 2
    rollout_length: int = 64
    num_env_runners: int = 2
    envs_per_runner: int = 4
    # ~0.5 updates per env step (512 steps/iteration at the defaults):
    # off-policy TD3 needs near-1:1 update:step ratio to make progress —
    # 1:16 plateaus at the random-policy return on Pendulum.
    updates_per_iteration: int = 256


def _mlp_init(sizes, key, out_scale=1.0):
    keys = jax.random.split(key, len(sizes))
    layers = []
    for i in range(len(sizes) - 1):
        scale = out_scale if i == len(sizes) - 2 else np.sqrt(2.0 / sizes[i])
        w = jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * scale
        layers.append({"w": w, "b": jnp.zeros(sizes[i + 1])})
    return {"layers": layers}


def _mlp_forward(params, x):
    for layer in params["layers"][:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    last = params["layers"][-1]
    return x @ last["w"] + last["b"]


def actor_action(params, obs, max_action: float):
    """Deterministic tanh policy scaled to the torque range."""
    return max_action * jnp.tanh(_mlp_forward(params["actor"], obs))


def _critic(params_q, obs, action):
    return _mlp_forward(params_q, jnp.concatenate([obs, action],
                                                  axis=-1))[..., 0]


def init_td3(config: TD3Config, key) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    a_sizes = (config.obs_dim,) + config.hidden + (config.action_dim,)
    q_sizes = ((config.obs_dim + config.action_dim,) + config.hidden + (1,))
    return {
        "actor": _mlp_init(a_sizes, k1, out_scale=1e-2),
        "q1": _mlp_init(q_sizes, k2),
        "q2": _mlp_init(q_sizes, k3),
    }


def make_update_fn(config: TD3Config, optimizer):
    gamma, tau = config.gamma, config.tau
    max_a = config.max_action

    def critic_loss(params, target_params, batch, key):
        noise = jnp.clip(
            config.target_noise * jax.random.normal(
                key, batch["actions"].shape),
            -config.target_noise_clip, config.target_noise_clip)
        next_a = jnp.clip(
            actor_action(target_params, batch["next_obs"], max_a) + noise,
            -max_a, max_a)
        tq = jnp.minimum(_critic(target_params["q1"], batch["next_obs"],
                                 next_a),
                         _critic(target_params["q2"], batch["next_obs"],
                                 next_a))
        target = batch["rewards"] + gamma * (1 - batch["dones"]) * tq
        target = jax.lax.stop_gradient(target)
        q1 = _critic(params["q1"], batch["obs"], batch["actions"])
        q2 = _critic(params["q2"], batch["obs"], batch["actions"])
        return ((q1 - target) ** 2 + (q2 - target) ** 2).mean(), (q1.mean(),)

    def actor_loss(params, batch):
        a = actor_action(params, batch["obs"], max_a)
        return -_critic(params["q1"], batch["obs"], a).mean()

    @jax.jit
    def update(params, target_params, opt_state, batch, key, step):
        (c_loss, (q_mean,)), c_grads = jax.value_and_grad(
            critic_loss, has_aux=True)(params, target_params, batch, key)
        a_loss, a_grads = jax.value_and_grad(actor_loss)(params, batch)

        # Critic grads always apply; actor grads only on delayed steps —
        # zeroing them inside ONE optimizer update keeps opt_state shapes
        # static (lax.cond over pytrees of identical structure).
        def delayed(_):
            return a_grads["actor"]

        def not_delayed(_):
            return jax.tree.map(jnp.zeros_like, a_grads["actor"])

        do_actor = (step % config.policy_delay) == 0
        grads = {"actor": jax.lax.cond(do_actor, delayed, not_delayed,
                                       None),
                 "q1": c_grads["q1"], "q2": c_grads["q2"]}
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)

        def soft(_):
            return jax.tree.map(lambda t, p: (1 - tau) * t + tau * p,
                                target_params, params)

        def keep(_):
            return target_params

        target_params = jax.lax.cond(do_actor, soft, keep, None)
        metrics = {"critic_loss": c_loss, "actor_loss": a_loss,
                   "q_mean": q_mean}
        return params, target_params, opt_state, metrics

    return update


class TD3Runner:
    """Actor: deterministic policy + gaussian exploration noise."""

    def __init__(self, config: TD3Config, seed: int):
        from ray_tpu.rl.env import make_env

        self.config = config
        self.env = make_env(config.env, config.envs_per_runner, seed)
        self.obs = self.env.reset()
        self.forward = jax.jit(
            lambda p, o: actor_action(p, o, config.max_action))
        self.rng = np.random.default_rng(seed)
        self.episode_returns = []
        self._running = np.zeros(config.envs_per_runner)

    def rollout(self, params) -> Dict[str, np.ndarray]:
        cfg = self.config
        obs_b, act_b, rew_b, done_b, next_b = [], [], [], [], []
        for _ in range(cfg.rollout_length):
            a = np.asarray(self.forward(params, jnp.asarray(self.obs)))
            a = np.clip(a + self.rng.normal(
                0, cfg.exploration_noise * cfg.max_action, a.shape),
                -cfg.max_action, cfg.max_action).astype(np.float32)
            next_obs, reward, done = self.env.step(a)
            obs_b.append(self.obs); act_b.append(a)
            # Time-limit truncations are NOT terminals: the critic target
            # must keep bootstrapping through them (zeroing it injects a
            # state-uncorrelated value bias at arbitrary cut points —
            # Pardo 2018). `done` still drives episode accounting below.
            learner_done = (np.zeros_like(done, dtype=np.float32)
                            if getattr(self.env,
                                       "all_dones_are_truncations", False)
                            else done.astype(np.float32))
            rew_b.append(reward); done_b.append(learner_done)
            next_b.append(next_obs)
            self._running += reward
            for i in np.where(done)[0]:
                self.episode_returns.append(float(self._running[i]))
                self._running[i] = 0.0
            self.obs = self.env.current_obs()
        return {
            "obs": np.concatenate(obs_b).astype(np.float32),
            "actions": np.concatenate(act_b).astype(np.float32),
            "rewards": np.concatenate(rew_b).astype(np.float32),
            "dones": np.concatenate(done_b).astype(np.float32),
            "next_obs": np.concatenate(next_b).astype(np.float32),
            "episode_returns": self.episode_returns[-50:],
        }


class TD3:
    def __init__(self, config: TD3Config):
        import optax

        import ray_tpu
        from ray_tpu.rl.replay_buffer import ReplayBuffer

        self.config = config
        self.params = init_td3(config, jax.random.key(0))
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.update_fn = make_update_fn(config, self.optimizer)
        self.buffer = ReplayBuffer(config.buffer_capacity)
        Runner = ray_tpu.remote(TD3Runner)
        self.runners = [Runner.remote(config, seed=i)
                        for i in range(config.num_env_runners)]
        self.env_steps = 0
        self.update_steps = 0
        self.iteration = 0
        self._key = jax.random.key(1)

    def train(self) -> Dict:
        import time

        import ray_tpu

        t0 = time.perf_counter()
        params_host = jax.tree.map(np.asarray, self.params)
        refs = [r.rollout.remote(params_host) for r in self.runners]
        episode_returns = []
        for ref in refs:
            roll = ray_tpu.get(ref, timeout=300)
            episode_returns.extend(roll.pop("episode_returns"))
            self.env_steps += len(roll["obs"])
            self.buffer.add_batch(roll)
        metrics_acc = {}
        if len(self.buffer) >= self.config.learning_starts:
            for _ in range(self.config.updates_per_iteration):
                batch = {k: jnp.asarray(v) for k, v in
                         self.buffer.sample(
                             self.config.train_batch_size).items()}
                self._key, sub = jax.random.split(self._key)
                self.params, self.target_params, self.opt_state, metrics = \
                    self.update_fn(self.params, self.target_params,
                                   self.opt_state, batch, sub,
                                   self.update_steps)
                self.update_steps += 1
                metrics_acc = {k: float(v) for k, v in metrics.items()}
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "num_env_steps": self.env_steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **metrics_acc,
        }

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
