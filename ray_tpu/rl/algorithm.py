"""Algorithm driver: EnvRunner actors + compiled Learner.

Reference analog: rllib Algorithm (algorithms/algorithm.py:199) with
EnvRunnerGroup (env/env_runner_group.py:71) and LearnerGroup
(core/learner/learner_group.py:79). Round-1 shape: N env-runner actors
collect rollouts with broadcast weights; one learner process (the driver or
a learner actor) runs the jit-compiled PPO update; a fault-tolerant manager
restarts dead runners.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl import ppo as ppo_mod
from ray_tpu.rl.env import make_env

logger = logging.getLogger(__name__)


class EnvRunner:
    """Actor: collects one rollout per call with the given weights."""

    def __init__(self, config: ppo_mod.PPOConfig, seed: int):
        import jax

        self.config = config
        self.env = make_env(config.env, config.envs_per_runner, seed)
        self.obs = self.env.reset()
        self.forward = jax.jit(ppo_mod.policy_forward)
        self.rng = np.random.default_rng(seed)
        self.episode_returns: List[float] = []
        self._running_return = np.zeros(config.envs_per_runner)

    def rollout(self, params) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        T = self.config.rollout_length
        obs_buf, act_buf, logp_buf, rew_buf, done_buf, val_buf = \
            [], [], [], [], [], []
        for _ in range(T):
            logits, values = self.forward(params, jnp.asarray(self.obs))
            logits = np.asarray(logits)
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            actions = np.array([self.rng.choice(len(p), p=p) for p in probs])
            logp = np.log(probs[np.arange(len(actions)), actions] + 1e-10)
            next_obs, reward, done = self.env.step(actions)
            obs_buf.append(self.obs)
            act_buf.append(actions)
            logp_buf.append(logp)
            rew_buf.append(reward)
            done_buf.append(done.astype(np.float32))
            val_buf.append(np.asarray(values))
            self._running_return += reward
            for i in np.where(done)[0]:
                self.episode_returns.append(float(self._running_return[i]))
                self._running_return[i] = 0.0
            # next_obs keeps terminal rows (the true s'); act next on
            # the post-auto-reset state or boundary transitions corrupt.
            self.obs = self.env.current_obs()
        _, last_value = self.forward(params, jnp.asarray(self.obs))
        return {
            "obs": np.stack(obs_buf),
            "actions": np.stack(act_buf),
            "logp_old": np.stack(logp_buf),
            "rewards": np.stack(rew_buf),
            "dones": np.stack(done_buf),
            "values": np.stack(val_buf),
            "last_value": np.asarray(last_value),
            "episode_returns": self.episode_returns[-50:],
        }


class PPO:
    """The Algorithm: train() runs one iteration (rollouts + update)."""

    def __init__(self, config: ppo_mod.PPOConfig):
        import jax
        import optax

        self.config = config
        self.params = ppo_mod.init_policy(config, jax.random.key(0))
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.update_fn = ppo_mod.make_update_fn(config, self.optimizer)
        self.key = jax.random.key(1)
        Runner = ray_tpu.remote(EnvRunner)
        self.runners = [Runner.remote(config, seed=i)
                        for i in range(config.num_env_runners)]
        self.iteration = 0

    def train(self) -> Dict:
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        rollouts = self._collect_rollouts()
        gae_in = [(r["rewards"], r["values"], r["dones"], r["last_value"])
                  for r in rollouts]
        batches = []
        episode_returns: List[float] = []
        for r in rollouts:
            adv, ret = ppo_mod.compute_gae(
                jnp.asarray(r["rewards"]), jnp.asarray(r["values"]),
                jnp.asarray(r["dones"]), jnp.asarray(r["last_value"]),
                self.config.gamma, self.config.gae_lambda)
            flat = {
                "obs": r["obs"].reshape(-1, self.config.obs_dim),
                "actions": r["actions"].reshape(-1).astype(np.int32),
                "logp_old": r["logp_old"].reshape(-1).astype(np.float32),
                "advantages": np.asarray(adv).reshape(-1),
                "returns": np.asarray(ret).reshape(-1),
            }
            batches.append(flat)
            episode_returns.extend(r["episode_returns"])
        batch = {k: jnp.asarray(np.concatenate([b[k] for b in batches]))
                 for k in batches[0]}
        self.key, subkey = jax.random.split(self.key)
        self.params, self.opt_state, metrics = self.update_fn(
            self.params, self.opt_state, batch, subkey)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "num_env_steps": int(batch["obs"].shape[0]),
            "time_this_iter_s": time.perf_counter() - t0,
            **{k: float(v) for k, v in metrics.items()},
        }

    def _collect_rollouts(self) -> List[Dict]:
        """FaultTolerantActorManager-lite: dead runners are replaced and the
        round retried on the survivors + replacements."""
        import jax

        params_host = jax.tree.map(np.asarray, self.params)
        for attempt in range(3):
            refs = [r.rollout.remote(params_host) for r in self.runners]
            results, failed = [], []
            for i, ref in enumerate(refs):
                try:
                    results.append(ray_tpu.get(ref, timeout=300))
                except ray_tpu.RayTpuError:
                    failed.append(i)
            if not failed:
                return results
            logger.warning("replacing %d dead env runners", len(failed))
            Runner = ray_tpu.remote(EnvRunner)
            for i in failed:
                self.runners[i] = Runner.remote(self.config,
                                                seed=100 + attempt * 10 + i)
        raise RuntimeError("env runners kept dying")

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
