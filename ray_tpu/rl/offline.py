"""Offline RL: episode storage, BC and MARWIL learners.

Reference analog: rllib/offline/ (episode writers/readers feeding offline
algorithms) and rllib/algorithms/{bc,marwil}/. TPU-native shape: episodes
are columnar .npz shards on disk; learners are single jit-compiled update
functions over stacked batches (the pjit-learner pattern shared with
rl/ppo.py), so the same code path scales over a mesh's data axes.

MARWIL loss: advantage-weighted behavioral cloning —
    L = -E[ exp(beta * A_norm) * log pi(a|s) ] + vf_coef * E[(V(s) - R)^2]
with A = R_monte_carlo - V(s); beta=0 degenerates to plain BC + value fit.
BC is the beta=0 special case without the value head term.
"""

from __future__ import annotations

import dataclasses
import glob
import os
from functools import partial
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.ppo import PPOConfig, init_policy, policy_forward

# ------------------------------------------------------------ episode I/O


class EpisodeWriter:
    """Buffers transitions and writes columnar shards — collect_episodes
    stores {obs, actions, rewards, dones, next_obs} per shard
    (SampleBatch-shaped; next_obs keeps terminal states so TD learners get
    complete transitions)."""

    def __init__(self, path: str, shard_size: int = 4096):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.shard_size = shard_size
        self._buf: Dict[str, List[np.ndarray]] = {}
        self._count = 0
        self._shard = 0

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(next(iter(batch.values())))
        for k, v in batch.items():
            self._buf.setdefault(k, []).append(np.asarray(v))
        self._count += n
        if self._count >= self.shard_size:
            self.flush()

    def flush(self):
        if not self._count:
            return
        arrays = {k: np.concatenate(v) for k, v in self._buf.items()}
        out = os.path.join(self.path, f"shard_{self._shard:05d}.npz")
        np.savez_compressed(out + ".tmp.npz", **arrays)
        os.replace(out + ".tmp.npz", out)
        self._shard += 1
        self._buf.clear()
        self._count = 0


def read_episodes(path: str) -> Dict[str, np.ndarray]:
    """Load all shards into one columnar batch."""
    shards = sorted(glob.glob(os.path.join(path, "shard_*.npz")))
    if not shards:
        raise FileNotFoundError(f"no episode shards under {path}")
    cols: Dict[str, List[np.ndarray]] = {}
    for s in shards:
        with np.load(s) as z:
            for k in z.files:
                cols.setdefault(k, []).append(z[k])
    return {k: np.concatenate(v) for k, v in cols.items()}


def iterate_minibatches(rng: np.random.Generator, batch: Dict[str, np.ndarray],
                        batch_size: int, epochs: int) -> Iterator[Dict]:
    """Shuffled drop-remainder minibatches over a columnar batch, shared by
    the offline trainers (MARWIL/BC here, CQL in rl/cql.py) so epoch
    semantics can't drift between them."""
    n = len(next(iter(batch.values())))
    bs = min(batch_size, n)
    for _ in range(epochs):
        idx = rng.permutation(n)
        for start in range(0, n - bs + 1, bs):
            yield {k: jnp.asarray(v[idx[start:start + bs]])
                   for k, v in batch.items()}


def monte_carlo_returns(rewards: np.ndarray, dones: np.ndarray,
                        gamma: float) -> np.ndarray:
    """Per-step discounted return-to-go, resetting at episode boundaries."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        acc = rewards[i] + gamma * acc * (1.0 - dones[i])
        out[i] = acc
    return out


# ------------------------------------------------------------ learners


@dataclasses.dataclass(frozen=True)
class MARWILConfig:
    obs_dim: int = 4
    n_actions: int = 2
    hidden: Tuple[int, ...] = (64, 64)
    beta: float = 1.0            # 0 => plain BC
    vf_coef: float = 1.0
    gamma: float = 0.99
    lr: float = 1e-3
    batch_size: int = 256
    epochs: int = 5


def _policy_cfg(config: MARWILConfig) -> PPOConfig:
    return PPOConfig(obs_dim=config.obs_dim, n_actions=config.n_actions,
                     hidden=config.hidden)


def marwil_loss(params, batch, config: MARWILConfig):
    logits, values = policy_forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
    adv = batch["returns"] - values
    if config.beta > 0.0:
        # Normalize advantages by a running-free batch estimate; clip the
        # exponent for stability (rllib clips at 20).
        norm = jnp.sqrt(jnp.mean(adv ** 2) + 1e-8)
        weights = jnp.exp(jnp.clip(config.beta * adv / norm, -20.0, 20.0))
        weights = jax.lax.stop_gradient(weights)
    else:
        weights = jnp.ones_like(adv)
    policy_loss = -jnp.mean(weights * logp)
    vf_loss = jnp.mean(adv ** 2)
    total = policy_loss + config.vf_coef * vf_loss
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss}


def make_marwil_update(config: MARWILConfig, optimizer):
    @jax.jit
    def update(params, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(
            marwil_loss, has_aux=True)(params, batch, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss, **aux}

    return update


class MARWIL:
    """Offline trainer: fit a policy to stored episodes."""

    def __init__(self, config: MARWILConfig, data_path: str, seed: int = 0):
        self.config = config
        data = read_episodes(data_path)
        self.batch = {
            "obs": data["obs"].astype(np.float32),
            "actions": data["actions"].astype(np.int32),
            "returns": monte_carlo_returns(
                data["rewards"].astype(np.float32),
                data["dones"].astype(np.float32), config.gamma),
        }
        self.params = init_policy(_policy_cfg(config), jax.random.key(seed))
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.update = make_marwil_update(config, self.optimizer)
        self.rng = np.random.default_rng(seed)

    def train(self) -> Dict:
        metrics = {}
        for mb in iterate_minibatches(self.rng, self.batch,
                                      self.config.batch_size,
                                      self.config.epochs):
            self.params, self.opt_state, metrics = self.update(
                self.params, self.opt_state, mb)
        return {k: float(v) for k, v in metrics.items()}

    def action_logits(self, obs: np.ndarray) -> np.ndarray:
        logits, _ = policy_forward(self.params, jnp.asarray(obs))
        return np.asarray(logits)


class BC(MARWIL):
    """Behavioral cloning = MARWIL with beta=0 (rllib/algorithms/bc)."""

    def __init__(self, config: Optional[MARWILConfig] = None,
                 data_path: str = "", seed: int = 0, **overrides):
        base = config or MARWILConfig()
        base = dataclasses.replace(base, beta=0.0, vf_coef=overrides.pop(
            "vf_coef", 0.0), **overrides)
        super().__init__(base, data_path, seed)


def collect_episodes(env_name: str, path: str, *, n_steps: int = 2048,
                     policy=None, config: Optional[PPOConfig] = None,
                     seed: int = 0) -> str:
    """Roll a (possibly random) policy in an env and persist episodes —
    the offline-data generation utility tests and examples use."""
    from ray_tpu.rl.env import make_env

    cfg = config or PPOConfig()
    env = make_env(env_name, 8, seed)
    obs = env.reset()
    writer = EpisodeWriter(path)
    rng = np.random.default_rng(seed)
    fwd = jax.jit(policy_forward) if policy is not None else None
    for _ in range(n_steps // 8):
        if policy is not None:
            logits = np.asarray(fwd(policy, jnp.asarray(obs))[0])
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            actions = np.array([rng.choice(len(p), p=p) for p in probs])
        else:
            actions = rng.integers(0, cfg.n_actions, size=len(obs))
        next_obs, reward, done = env.step(actions)
        writer.add_batch({"obs": obs, "actions": actions, "rewards": reward,
                          "dones": done.astype(np.float32),
                          "next_obs": next_obs})
        # next_obs keeps terminal rows (the true s' for the stored
        # transition); act next on the post-auto-reset state.
        obs = env.current_obs()
    writer.flush()
    return path
