"""Built-in vectorized environments (no gym dependency).

Reference analog: RLlib's env layer (rllib/env/); CartPole is the standard
smoke-test task (tuned_examples/ppo/cartpole_ppo.py equivalents).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class VectorCartPole:
    """Classic CartPole-v1 dynamics, vectorized over n_envs, numpy only."""

    obs_dim = 4
    n_actions = 2
    max_steps = 500

    def __init__(self, n_envs: int, seed: int = 0):
        self.n = n_envs
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros((n_envs, 4), dtype=np.float32)
        self.steps = np.zeros(n_envs, dtype=np.int64)
        self.reset()

    def reset(self) -> np.ndarray:
        self.state = self.rng.uniform(-0.05, 0.05, (self.n, 4)).astype(np.float32)
        self.steps[:] = 0
        return self.state.copy()

    def _reset_done(self, done: np.ndarray):
        k = int(done.sum())
        if k:
            self.state[done] = self.rng.uniform(-0.05, 0.05, (k, 4)).astype(
                np.float32)
            self.steps[done] = 0

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        g, mc, mp, length, fmag, tau = 9.8, 1.0, 0.1, 0.5, 10.0, 0.02
        total_m = mc + mp
        pml = mp * length
        x, x_dot, th, th_dot = self.state.T
        force = np.where(actions == 1, fmag, -fmag)
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + pml * th_dot ** 2 * sin) / total_m
        th_acc = (g * sin - cos * temp) / (
            length * (4.0 / 3.0 - mp * cos ** 2 / total_m))
        x_acc = temp - pml * th_acc * cos / total_m
        x = x + tau * x_dot
        x_dot = x_dot + tau * x_acc
        th = th + tau * th_dot
        th_dot = th_dot + tau * th_acc
        self.state = np.stack([x, x_dot, th, th_dot], axis=1).astype(np.float32)
        self.steps += 1
        done = (np.abs(x) > 2.4) | (np.abs(th) > 0.2095) | \
            (self.steps >= self.max_steps)
        reward = np.ones(self.n, dtype=np.float32)
        obs = self.state.copy()  # TRUE next state (terminal rows included)
        self._reset_done(done)
        return obs, reward, done

    def current_obs(self) -> np.ndarray:
        """Observation to act on NEXT step: equals step()'s returned obs for
        live rows and the post-auto-reset state for done rows. Runners must
        use this (not the returned obs) to continue the rollout — carrying
        the terminal observation across an episode boundary pairs a dead
        episode's state with the fresh episode's dynamics."""
        return self.state.copy()


ENVS = {"CartPole-v1": VectorCartPole}


def make_env(name: str, n_envs: int, seed: int = 0):
    return ENVS[name](n_envs, seed)
