"""Built-in vectorized environments (no gym dependency).

Reference analog: RLlib's env layer (rllib/env/); CartPole is the standard
smoke-test task (tuned_examples/ppo/cartpole_ppo.py equivalents).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class VectorCartPole:
    """Classic CartPole-v1 dynamics, vectorized over n_envs, numpy only."""

    obs_dim = 4
    n_actions = 2
    max_steps = 500

    def __init__(self, n_envs: int, seed: int = 0):
        self.n = n_envs
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros((n_envs, 4), dtype=np.float32)
        self.steps = np.zeros(n_envs, dtype=np.int64)
        self.reset()

    def reset(self) -> np.ndarray:
        self.state = self.rng.uniform(-0.05, 0.05, (self.n, 4)).astype(np.float32)
        self.steps[:] = 0
        return self.state.copy()

    def _reset_done(self, done: np.ndarray):
        k = int(done.sum())
        if k:
            self.state[done] = self.rng.uniform(-0.05, 0.05, (k, 4)).astype(
                np.float32)
            self.steps[done] = 0

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        g, mc, mp, length, fmag, tau = 9.8, 1.0, 0.1, 0.5, 10.0, 0.02
        total_m = mc + mp
        pml = mp * length
        x, x_dot, th, th_dot = self.state.T
        force = np.where(actions == 1, fmag, -fmag)
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + pml * th_dot ** 2 * sin) / total_m
        th_acc = (g * sin - cos * temp) / (
            length * (4.0 / 3.0 - mp * cos ** 2 / total_m))
        x_acc = temp - pml * th_acc * cos / total_m
        x = x + tau * x_dot
        x_dot = x_dot + tau * x_acc
        th = th + tau * th_dot
        th_dot = th_dot + tau * th_acc
        self.state = np.stack([x, x_dot, th, th_dot], axis=1).astype(np.float32)
        self.steps += 1
        done = (np.abs(x) > 2.4) | (np.abs(th) > 0.2095) | \
            (self.steps >= self.max_steps)
        reward = np.ones(self.n, dtype=np.float32)
        obs = self.state.copy()  # TRUE next state (terminal rows included)
        self._reset_done(done)
        return obs, reward, done

    def current_obs(self) -> np.ndarray:
        """Observation to act on NEXT step: equals step()'s returned obs for
        live rows and the post-auto-reset state for done rows. Runners must
        use this (not the returned obs) to continue the rollout — carrying
        the terminal observation across an episode boundary pairs a dead
        episode's state with the fresh episode's dynamics."""
        return self.state.copy()


class VectorPendulum:
    """Classic Pendulum-v1 dynamics, vectorized, numpy only: CONTINUOUS
    torque in [-max_torque, max_torque], obs (cos th, sin th, th_dot),
    fixed 200-step episodes (no early termination) — the standard smoke
    test for continuous-control algorithms (TD3/DDPG/continuous SAC)."""

    obs_dim = 3
    action_dim = 1
    max_torque = 2.0
    max_steps = 200
    # Every done is a TIME-LIMIT truncation, not a terminal state:
    # off-policy learners must keep bootstrapping through it (Pardo 2018
    # time-limit handling; the original TD3 code zeroes done at limits).
    all_dones_are_truncations = True

    def __init__(self, n_envs: int, seed: int = 0):
        self.n = n_envs
        self.rng = np.random.default_rng(seed)
        self.th = np.zeros(n_envs, dtype=np.float32)
        self.th_dot = np.zeros(n_envs, dtype=np.float32)
        self.steps = np.zeros(n_envs, dtype=np.int64)
        self.reset()

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self.th), np.sin(self.th), self.th_dot],
                        axis=1).astype(np.float32)

    def reset(self) -> np.ndarray:
        self.th = self.rng.uniform(-np.pi, np.pi, self.n).astype(np.float32)
        self.th_dot = self.rng.uniform(-1, 1, self.n).astype(np.float32)
        self.steps[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        g, m, length, dt = 10.0, 1.0, 1.0, 0.05
        u = np.clip(np.asarray(actions, dtype=np.float32).reshape(self.n),
                    -self.max_torque, self.max_torque)
        th_norm = ((self.th + np.pi) % (2 * np.pi)) - np.pi
        reward = -(th_norm ** 2 + 0.1 * self.th_dot ** 2
                   + 0.001 * u ** 2).astype(np.float32)
        th_dot = self.th_dot + (3 * g / (2 * length) * np.sin(self.th)
                                + 3.0 / (m * length ** 2) * u) * dt
        th_dot = np.clip(th_dot, -8.0, 8.0)
        self.th = (self.th + th_dot * dt).astype(np.float32)
        self.th_dot = th_dot.astype(np.float32)
        self.steps += 1
        done = self.steps >= self.max_steps
        obs = self._obs()  # TRUE next state
        if done.any():
            k = int(done.sum())
            self.th[done] = self.rng.uniform(-np.pi, np.pi, k)
            self.th_dot[done] = self.rng.uniform(-1, 1, k)
            self.steps[done] = 0
        return obs, reward, done

    def current_obs(self) -> np.ndarray:
        return self._obs()


ENVS = {"CartPole-v1": VectorCartPole, "Pendulum-v1": VectorPendulum}


def make_env(name: str, n_envs: int, seed: int = 0):
    return ENVS[name](n_envs, seed)
