"""IMPALA: asynchronous actor-learner with V-trace off-policy correction.

Reference analog: rllib/algorithms/impala/ (IMPALA + vtrace). Runners
produce rollouts continuously with (stale) broadcast weights; the learner
consumes whichever rollouts are ready each step and corrects the policy lag
with V-trace (Espeholt et al. 2018), computed inside the jit-compiled
update via lax.scan (sequential bootstrap, compiler-friendly).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import ppo as ppo_mod


@dataclass
class ImpalaConfig:
    env: str = "CartPole-v1"
    obs_dim: int = 4
    n_actions: int = 2
    hidden: Tuple[int, ...] = (64, 64)
    gamma: float = 0.99
    lr: float = 5e-4
    vf_coef: float = 0.5
    entropy_coef: float = 0.01
    rho_clip: float = 1.0                 # V-trace importance clips
    c_clip: float = 1.0
    rollout_length: int = 64
    num_env_runners: int = 2
    envs_per_runner: int = 4
    max_requests_in_flight: int = 2       # async pipeline depth per runner


def vtrace(behaviour_logp, target_logp, rewards, values, dones, last_value,
           gamma, rho_clip, c_clip):
    """V-trace targets vs and advantages, shapes [T, B]."""
    rho = jnp.exp(target_logp - behaviour_logp)
    rho_bar = jnp.minimum(rho, rho_clip)
    c_bar = jnp.minimum(rho, c_clip)
    next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
    discounts = gamma * (1.0 - dones)
    deltas = rho_bar * (rewards + discounts * next_values - values)

    def scan_fn(acc, inp):
        delta_t, discount_t, c_t = inp
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(last_value),
        (deltas, discounts, c_bar), reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    advantages = rho_bar * (rewards + discounts * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(advantages)


def vtrace_prelude(params, batch, config):
    """Shared forward + V-trace scaffolding for IMPALA-family losses
    (IMPALA's plain PG, APPO's clipped surrogate). Returns
    (target_logp, logp_all, values, vs, adv)."""
    T, B = batch["rewards"].shape
    obs = batch["obs"].reshape(T * B, -1)
    logits, values_flat = ppo_mod.policy_forward(params, obs)
    logits = logits.reshape(T, B, -1)
    values = values_flat.reshape(T, B)
    logp_all = jax.nn.log_softmax(logits)
    target_logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None], axis=-1)[..., 0]
    _, last_value = ppo_mod.policy_forward(params, batch["last_obs"])
    vs, adv = vtrace(batch["behaviour_logp"], target_logp,
                     batch["rewards"], values, batch["dones"], last_value,
                     config.gamma, config.rho_clip, config.c_clip)
    return target_logp, logp_all, values, vs, adv


def make_update_fn(config: ImpalaConfig, optimizer, pg_loss_fn=None):
    """`pg_loss_fn(target_logp, behaviour_logp, adv) -> (loss, extra_metrics)`
    swaps the policy-gradient term (APPO passes the clipped surrogate)."""

    def loss_fn(params, batch):
        target_logp, logp_all, values, vs, adv = vtrace_prelude(
            params, batch, config)
        if pg_loss_fn is None:
            pg_loss = -(jax.lax.stop_gradient(adv) * target_logp).mean()
            extra = {}
        else:
            pg_loss, extra = pg_loss_fn(target_logp,
                                        batch["behaviour_logp"], adv)
        vf_loss = ((values - vs) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pg_loss + config.vf_coef * vf_loss \
            - config.entropy_coef * entropy
        return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy, **extra}

    @jax.jit
    def update(params, opt_state, batch):
        import optax

        (_, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    return update


class ImpalaRunner:
    """Actor: rollouts with the weights it was handed (possibly stale)."""

    def __init__(self, config: ImpalaConfig, seed: int):
        from ray_tpu.rl.env import make_env

        self.config = config
        self.env = make_env(config.env, config.envs_per_runner, seed)
        self.obs = self.env.reset()
        self.forward = jax.jit(ppo_mod.policy_forward)
        self.rng = np.random.default_rng(seed)
        self.episode_returns = []
        self._running = np.zeros(config.envs_per_runner)

    def rollout(self, params) -> Dict[str, np.ndarray]:
        T = self.config.rollout_length
        obs_b, act_b, logp_b, rew_b, done_b = [], [], [], [], []
        for _ in range(T):
            logits, _ = self.forward(params, jnp.asarray(self.obs))
            logits = np.asarray(logits)
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            actions = np.array([self.rng.choice(len(p), p=p) for p in probs])
            logp = np.log(probs[np.arange(len(actions)), actions] + 1e-10)
            next_obs, reward, done = self.env.step(actions)
            obs_b.append(self.obs); act_b.append(actions); logp_b.append(logp)
            rew_b.append(reward); done_b.append(done.astype(np.float32))
            self._running += reward
            for i in np.where(done)[0]:
                self.episode_returns.append(float(self._running[i]))
                self._running[i] = 0.0
            # next_obs keeps terminal rows (the true s'); act next on
            # the post-auto-reset state or boundary transitions corrupt.
            self.obs = self.env.current_obs()
        return {
            "obs": np.stack(obs_b).astype(np.float32),          # [T, B, D]
            "actions": np.stack(act_b).astype(np.int32),
            "behaviour_logp": np.stack(logp_b).astype(np.float32),
            "rewards": np.stack(rew_b).astype(np.float32),
            "dones": np.stack(done_b).astype(np.float32),
            "last_obs": self.obs.astype(np.float32),
            "episode_returns": self.episode_returns[-50:],
        }


class IMPALA:
    """Async pipeline: keep max_requests_in_flight rollouts outstanding per
    runner; each train() consumes one ready rollout and immediately
    re-dispatches with fresh weights."""

    def __init__(self, config: ImpalaConfig):
        import optax

        import ray_tpu

        pcfg = ppo_mod.PPOConfig(obs_dim=config.obs_dim,
                                 n_actions=config.n_actions,
                                 hidden=config.hidden)
        self.config = config
        self.params = ppo_mod.init_policy(pcfg, jax.random.key(0))
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.update_fn = make_update_fn(config, self.optimizer)
        Runner = ray_tpu.remote(ImpalaRunner)
        self.runners = [Runner.remote(config, seed=i)
                        for i in range(config.num_env_runners)]
        self._inflight: Dict = {}
        self.env_steps = 0
        self.iteration = 0
        self._dispatch_all()

    def _params_host(self):
        return jax.tree.map(np.asarray, self.params)

    def _dispatch_all(self):
        params_host = self._params_host()
        for r in self.runners:
            while sum(1 for v in self._inflight.values() if v is r) < \
                    self.config.max_requests_in_flight:
                self._inflight[r.rollout.remote(params_host)] = r

    def train(self) -> Dict:
        import time

        import ray_tpu

        t0 = time.perf_counter()
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                timeout=300)
        if not ready:
            raise TimeoutError("no rollout became ready")
        ref = ready[0]
        runner = self._inflight.pop(ref)
        roll = ray_tpu.get(ref)
        # Refill the pipeline with current weights before updating.
        self._inflight[runner.rollout.remote(self._params_host())] = runner
        episode_returns = roll.pop("episode_returns")
        self.env_steps += roll["rewards"].size
        batch = {k: jnp.asarray(v) for k, v in roll.items()}
        self.params, self.opt_state, metrics = self.update_fn(
            self.params, self.opt_state, batch)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "num_env_steps": self.env_steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **{k: float(v) for k, v in metrics.items()},
        }

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
