"""RL x Tune: run any algorithm under the Tuner.

Reference analog: rllib Algorithm extends tune.Trainable
(algorithms/algorithm.py:199 — "Algorithms can be interacted with in tune
via their string names"), so `Tuner(PPO, param_space=...)` hyperparameter-
sweeps RL. Ours adapts the (Config dataclass, Algorithm class) pairs into
a function trainable: the Tune config dict overrides dataclass fields, the
algorithm trains `iterations` steps, each reported to the session (so
ASHA/PBT schedulers see per-iteration metrics and can early-stop RL
trials).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Type

_REGISTRY: Dict[str, Any] = {}


def register_algorithm(name: str, algo_cls: Type, config_cls: Type):
    _REGISTRY[name] = (algo_cls, config_cls)


def _builtin(name: str):
    if not _REGISTRY:
        from ray_tpu.rl.algorithm import PPO
        from ray_tpu.rl.dqn import DQN, DQNConfig
        from ray_tpu.rl.impala import IMPALA, ImpalaConfig
        from ray_tpu.rl.ppo import PPOConfig
        from ray_tpu.rl.sac import SAC, SACConfig

        register_algorithm("PPO", PPO, PPOConfig)
        register_algorithm("DQN", DQN, DQNConfig)
        register_algorithm("SAC", SAC, SACConfig)
        register_algorithm("IMPALA", IMPALA, ImpalaConfig)
    return _REGISTRY[name]


def as_trainable(algorithm: str, base_config=None, *,
                 iterations: Optional[int] = None) -> Callable[[Dict], None]:
    """Build a Tune function-trainable for a registered algorithm.

    The returned fn merges the trial's config dict over `base_config`
    (dataclass field overrides only — unknown keys are ignored so search
    spaces can carry extra bookkeeping), trains, and reports every
    iteration with `training_iteration` set for scheduler rungs."""
    algo_cls, config_cls = _builtin(algorithm)
    base = base_config or config_cls()

    def _trainable(config: Dict):
        from ray_tpu import tune

        fields = {f.name for f in dataclasses.fields(config_cls)}
        overrides = {k: v for k, v in config.items() if k in fields}
        algo_config = dataclasses.replace(base, **overrides)
        n_iters = iterations or getattr(algo_config, "iterations", 10)
        algo = algo_cls(algo_config)
        try:
            for i in range(n_iters):
                metrics = dict(algo.train())
                metrics["training_iteration"] = i + 1
                tune.report(metrics)
        finally:
            try:
                algo.stop()
            except Exception:
                pass

    _trainable.__name__ = f"{algorithm.lower()}_trainable"
    return _trainable
