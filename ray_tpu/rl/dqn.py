"""DQN: double Q-learning with target network and (optionally prioritized)
replay, as a jit-compiled jax update.

Reference analog: rllib/algorithms/dqn/ (DQN + DQNTorchLearner); redesigned
for XLA — the whole update (double-DQN targets, Huber loss, grad step,
polyak target sync) is one compiled function so the MXU sees a single fused
graph per step.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class DQNConfig:
    env: str = "CartPole-v1"
    obs_dim: int = 4
    n_actions: int = 2
    hidden: Tuple[int, ...] = (64, 64)
    gamma: float = 0.99
    lr: float = 1e-3
    buffer_capacity: int = 50_000
    learning_starts: int = 500
    train_batch_size: int = 64
    target_update_tau: float = 0.01       # polyak every update
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_steps: int = 3_000
    rollout_length: int = 64
    num_env_runners: int = 2
    envs_per_runner: int = 4
    prioritized_replay: bool = False
    updates_per_iteration: int = 16


def init_q_network(config: DQNConfig, key) -> Dict:
    sizes = (config.obs_dim,) + config.hidden + (config.n_actions,)
    keys = jax.random.split(key, len(sizes))
    layers = []
    for i in range(len(sizes) - 1):
        w = jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * np.sqrt(
            2.0 / sizes[i])
        layers.append({"w": w, "b": jnp.zeros(sizes[i + 1])})
    return {"layers": layers}


def q_forward(params: Dict, obs: jax.Array) -> jax.Array:
    x = obs
    for layer in params["layers"][:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    last = params["layers"][-1]
    return x @ last["w"] + last["b"]


def double_dqn_target(params, target_params, batch, gamma: float):
    """Double-DQN TD target: the online net picks the argmax action, the
    target net evaluates it; (1-done) masks the bootstrap. Shared by DQN
    (online) and CQL (offline, rl/cql.py)."""
    next_q_online = q_forward(params, batch["next_obs"])
    next_actions = jnp.argmax(next_q_online, axis=1)
    next_q_target = q_forward(target_params, batch["next_obs"])
    next_q = jnp.take_along_axis(
        next_q_target, next_actions[:, None], axis=1)[:, 0]
    return batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
        jax.lax.stop_gradient(next_q)


def huber(td: jax.Array) -> jax.Array:
    return jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2, jnp.abs(td) - 0.5)


def make_update_fn(config: DQNConfig, optimizer):
    def loss_fn(params, target_params, batch):
        q = q_forward(params, batch["obs"])
        q_taken = jnp.take_along_axis(
            q, batch["actions"][:, None], axis=1)[:, 0]
        td = q_taken - double_dqn_target(params, target_params, batch,
                                         config.gamma)
        losses = huber(td)
        weights = batch.get("weights", jnp.ones_like(losses))
        return (weights * losses).mean(), td

    @jax.jit
    def update(params, target_params, opt_state, batch):
        (loss, td), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        tau = config.target_update_tau
        target_params = jax.tree.map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, params)
        return params, target_params, opt_state, {"loss": loss, "td": td}

    return update


class DQNRunner:
    """Actor: epsilon-greedy step collection (SingleAgentEnvRunner analog)."""

    def __init__(self, config: DQNConfig, seed: int):
        from ray_tpu.rl.env import make_env

        self.config = config
        self.env = make_env(config.env, config.envs_per_runner, seed)
        self.obs = self.env.reset()
        self.forward = jax.jit(q_forward)
        self.rng = np.random.default_rng(seed)
        self.episode_returns = []
        self._running = np.zeros(config.envs_per_runner)

    def rollout(self, params, epsilon: float) -> Dict[str, np.ndarray]:
        obs_b, act_b, rew_b, done_b, next_b = [], [], [], [], []
        for _ in range(self.config.rollout_length):
            q = np.asarray(self.forward(params, jnp.asarray(self.obs)))
            greedy = q.argmax(-1)
            random_a = self.rng.integers(0, self.config.n_actions,
                                         size=len(greedy))
            explore = self.rng.random(len(greedy)) < epsilon
            actions = np.where(explore, random_a, greedy)
            next_obs, reward, done = self.env.step(actions)
            obs_b.append(self.obs); act_b.append(actions)
            rew_b.append(reward); done_b.append(done.astype(np.float32))
            next_b.append(next_obs)
            self._running += reward
            for i in np.where(done)[0]:
                self.episode_returns.append(float(self._running[i]))
                self._running[i] = 0.0
            # next_obs keeps terminal rows (the true s'); act next on
            # the post-auto-reset state or boundary transitions corrupt.
            self.obs = self.env.current_obs()
        return {
            "obs": np.concatenate(obs_b).astype(np.float32),
            "actions": np.concatenate(act_b).astype(np.int32),
            "rewards": np.concatenate(rew_b).astype(np.float32),
            "dones": np.concatenate(done_b).astype(np.float32),
            "next_obs": np.concatenate(next_b).astype(np.float32),
            "episode_returns": self.episode_returns[-50:],
        }


class DQN:
    """train() = collect (parallel runners) + replay updates."""

    def __init__(self, config: DQNConfig):
        import optax

        import ray_tpu
        from ray_tpu.rl.replay_buffer import (
            PrioritizedReplayBuffer,
            ReplayBuffer,
        )

        self.config = config
        self.params = init_q_network(config, jax.random.key(0))
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.update_fn = make_update_fn(config, self.optimizer)
        self.buffer = (PrioritizedReplayBuffer(config.buffer_capacity)
                       if config.prioritized_replay
                       else ReplayBuffer(config.buffer_capacity))
        Runner = ray_tpu.remote(DQNRunner)
        self.runners = [Runner.remote(config, seed=i)
                        for i in range(config.num_env_runners)]
        self.env_steps = 0
        self.iteration = 0

    def epsilon(self) -> float:
        frac = min(1.0, self.env_steps / self.config.epsilon_decay_steps)
        return self.config.epsilon_start + frac * (
            self.config.epsilon_end - self.config.epsilon_start)

    def train(self) -> Dict:
        import time

        import ray_tpu

        t0 = time.perf_counter()
        params_host = jax.tree.map(np.asarray, self.params)
        eps = self.epsilon()
        refs = [r.rollout.remote(params_host, eps) for r in self.runners]
        episode_returns = []
        for ref in refs:
            roll = ray_tpu.get(ref, timeout=300)
            episode_returns.extend(roll.pop("episode_returns"))
            self.env_steps += len(roll["obs"])
            self.buffer.add_batch(roll)
        losses = []
        if len(self.buffer) >= self.config.learning_starts:
            for _ in range(self.config.updates_per_iteration):
                batch = self.buffer.sample(self.config.train_batch_size)
                indices = batch.pop("indices", None)
                jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
                self.params, self.target_params, self.opt_state, metrics = \
                    self.update_fn(self.params, self.target_params,
                                   self.opt_state, jbatch)
                losses.append(float(metrics["loss"]))
                if indices is not None:
                    self.buffer.update_priorities(
                        indices, np.asarray(metrics["td"]))
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "num_env_steps": self.env_steps,
            "epsilon": eps,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "time_this_iter_s": time.perf_counter() - t0,
        }

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
