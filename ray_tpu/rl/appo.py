"""APPO: asynchronous PPO on the IMPALA actor-learner substrate.

Reference analog: rllib/algorithms/appo/ — IMPALA's async rollout pipeline
(stale-weights runners, V-trace off-policy correction) combined with PPO's
clipped surrogate objective instead of the plain policy-gradient loss.
Reuses ImpalaRunner, the async dispatch loop, and the shared V-trace loss
prelude (impala.vtrace_prelude); only the policy-gradient term differs.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ray_tpu.rl import impala as impala_mod
from ray_tpu.rl.impala import IMPALA, ImpalaConfig


@dataclass
class APPOConfig(ImpalaConfig):
    clip_eps: float = 0.3                # PPO surrogate clip


def make_update_fn(config: APPOConfig, optimizer):
    def clipped_surrogate(target_logp, behaviour_logp, adv):
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        # Clip against the BEHAVIOUR policy: the rollout was collected
        # with stale weights (appo's is_ratio).
        ratio = jnp.exp(target_logp - behaviour_logp)
        clipped = jnp.clip(ratio, 1.0 - config.clip_eps,
                           1.0 + config.clip_eps)
        pg_loss = -jnp.minimum(ratio * adv, clipped * adv).mean()
        clip_frac = (jnp.abs(ratio - 1.0) > config.clip_eps).mean()
        return pg_loss, {"clip_frac": clip_frac}

    return impala_mod.make_update_fn(config, optimizer,
                                     pg_loss_fn=clipped_surrogate)


class APPO(IMPALA):
    """IMPALA's pipeline with the PPO surrogate update."""

    def __init__(self, config: APPOConfig):
        super().__init__(config)
        # Replace the IMPALA update with the clipped-surrogate one.
        self.update_fn = make_update_fn(config, self.optimizer)
