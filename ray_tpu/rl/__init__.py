from ray_tpu.rl.algorithm import PPO, EnvRunner  # noqa: F401
from ray_tpu.rl.env import VectorCartPole, make_env  # noqa: F401
from ray_tpu.rl.ppo import PPOConfig  # noqa: F401
