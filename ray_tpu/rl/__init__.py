from ray_tpu.rl.algorithm import PPO, EnvRunner  # noqa: F401
from ray_tpu.rl.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rl.env import VectorCartPole, make_env  # noqa: F401
from ray_tpu.rl.impala import IMPALA, ImpalaConfig  # noqa: F401
from ray_tpu.rl.ppo import PPOConfig  # noqa: F401
from ray_tpu.rl.replay_buffer import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rl.sac import SAC, SACConfig  # noqa: F401
