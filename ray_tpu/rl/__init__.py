from ray_tpu.rl.algorithm import PPO, EnvRunner  # noqa: F401
from ray_tpu.rl.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rl.connectors import (  # noqa: F401
    Connector,
    ConnectorPipeline,
    FrameStack,
    ObsNormalizer,
)
from ray_tpu.rl.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rl.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rl.dreamerv3 import DreamerV3, DreamerV3Config  # noqa: F401
from ray_tpu.rl.env import (  # noqa: F401
    VectorCartPole,
    VectorPendulum,
    make_env,
)
from ray_tpu.rl.impala import IMPALA, ImpalaConfig  # noqa: F401
from ray_tpu.rl.ppo import PPOConfig  # noqa: F401
from ray_tpu.rl.replay_buffer import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rl.offline import (  # noqa: F401
    BC,
    MARWIL,
    EpisodeWriter,
    MARWILConfig,
    collect_episodes,
    read_episodes,
)
from ray_tpu.rl.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rl.sac_continuous import (  # noqa: F401
    SACContinuous,
    SACContinuousConfig,
)
from ray_tpu.rl.td3 import TD3, TD3Config  # noqa: F401
from ray_tpu.rl.tune_integration import as_trainable, register_algorithm  # noqa: F401
