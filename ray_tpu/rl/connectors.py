"""Connectors: composable observation/action transform pipelines.

Reference analog: rllib/connectors/ (connectors v2 — env-to-module and
module-to-env pipelines attached to EnvRunners so preprocessing travels
with the policy, not the env). Ours are stateful numpy transforms with
(get_state/set_state) so weights broadcast alongside policy params.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]):
        pass

    def reset(self):
        """Called on episode boundaries (per-env state like frame stacks)."""


class ObsNormalizer(Connector):
    """Running mean/std normalization (Welford), updated on trajectories
    collected by env runners; inference uses frozen statistics."""

    def __init__(self, clip: float = 10.0, update: bool = True):
        self.clip = clip
        self.update = update
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, dtype=np.float32)
        flat = obs.reshape(-1, obs.shape[-1])
        if self.update:
            if self.mean is None:
                self.mean = np.zeros(obs.shape[-1], np.float64)
                self.m2 = np.ones(obs.shape[-1], np.float64)
            for row in flat:
                self.count += 1.0
                delta = row - self.mean
                self.mean += delta / self.count
                self.m2 += delta * (row - self.mean)
        if self.mean is None or self.count < 2:
            return obs
        std = np.sqrt(self.m2 / max(self.count - 1, 1.0)) + 1e-8
        out = (obs - self.mean.astype(np.float32)) / std.astype(np.float32)
        return np.clip(out, -self.clip, self.clip)

    def get_state(self):
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def set_state(self, state):
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


class FrameStack(Connector):
    """Stacks the last k observations along the feature axis (vector obs)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames: deque = deque(maxlen=k)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, dtype=np.float32)
        if not self._frames or self._frames[0].shape != obs.shape:
            self._frames = deque([obs] * self.k, maxlen=self.k)
        else:
            self._frames.append(obs)
        return np.concatenate(list(self._frames), axis=-1)

    def reset(self):
        self._frames.clear()


class ConnectorPipeline(Connector):
    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, obs):
        for c in self.connectors:
            obs = c(obs)
        return obs

    def get_state(self):
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])

    def reset(self):
        for c in self.connectors:
            c.reset()
