"""Project-invariant analysis layer: graftlint + runtime sanitizers.

The codebase rests on a handful of cross-cutting invariants that used to
be proven one counter-proof test at a time:

  * zero-pickle on hot wire paths (ring collectives, raw-frame RPC, KV
    handoffs, device channels, checkpoint manifests);
  * no blocking calls inside remote-actor ``__init__`` (the router
    deadlock class: an actor constructor that blocks on the very control
    plane that is constructing it);
  * forward-compatible typed frames in ``runtime/wire.py`` (field numbers
    are forever, every frame round-trips in CI);
  * every event type documented, every metric's tags declared, every
    background thread daemonized and named.

``graftlint`` enforces these statically over the whole package — AST
passes, no imports of the code under analysis — and the sanitizers
enforce the dynamic halves at test time:

  * :class:`PickleSanitizer` hooks pickle during a scoped window and
    attributes every (de)serialization to its call site;
  * :class:`LockOrderSanitizer` wraps ``threading.Lock`` and reports
    cross-thread lock-order inversions with both acquisition stacks.

CLI: ``python -m ray_tpu.scripts lint [--json]``. Docs:
``docs/static_analysis.md``.
"""

from ray_tpu.analysis.graftlint import LintConfig, LintResult, Violation, run
from ray_tpu.analysis.sanitizers import (LockOrderSanitizer, PickleSanitizer,
                                         pickle_window)

__all__ = [
    "LintConfig", "LintResult", "Violation", "run",
    "PickleSanitizer", "LockOrderSanitizer", "pickle_window",
]
