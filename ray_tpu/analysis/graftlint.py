"""graftlint: AST-based checker for the project's cross-cutting invariants.

Reference analog: Ray's scattered CI lint scripts (ci/lint/*,
check_api_annotations, the banned-words checks) — here consolidated into
one analysis pass over the package with machine-checkable rules. Every
pass is pure AST + text: linting never imports the code under analysis,
so it runs in milliseconds and cannot be confused by import-time side
effects.

Rules
-----
hot-pickle            pickle/cloudpickle calls inside the frozen list of
                      zero-pickle hot-path modules (ring collectives,
                      raw-frame RPC, device channels, KV handoff,
                      checkpoint manifest).
actor-init-blocking   ray_tpu.get()/wait(), handle resolution
                      (replica_handles), or collective group ops inside a
                      @remote / deployment class __init__ — including
                      self-helper methods reachable from __init__. This is
                      the router deadlock class: a constructor blocking on
                      the control plane that is mid-way through
                      constructing it.
wire-field-order      *Msg field numbers in runtime/wire.py must be
                      declared in ascending order with no duplicates
                      (numbers are wire identity; declaration order is the
                      reader's mental schema — keep them aligned).
wire-field-default    Field(default=...) must be an immutable literal; a
                      mutable default would be shared across instances.
wire-roundtrip        every *Msg class must have an entry in the
                      roundtrip-test registry (WIRE_ROUNDTRIP_REGISTRY in
                      tests/test_wire_schema.py) so CI proves it
                      encodes/decodes.
event-docs            every type in runtime/events.py EVENT_TYPES must
                      have a row in docs/observability.md.
event-undeclared      emit()/make_event() called with a string literal
                      that is not a registered event type.
metric-def            metric_defs.py hygiene: ray_tpu_-prefixed name,
                      non-empty description, literal tag_keys tuple.
metric-docs           every metric declared in runtime/metric_defs.py must
                      have a backticked row in docs/observability.md (the
                      event-docs discipline, applied to metrics).
metric-central        Counter/Gauge/Histogram constructed outside
                      runtime/metric_defs.py (runtime metrics are defined
                      once, in the central table).
metric-tags           a metric observation (.inc/.set/.observe/.bind)
                      passing literal tag keys not declared by the metric.
alert-def             runtime/alert_defs.py hygiene: every rule in
                      ALERT_RULES must be a literal dict whose series is
                      a metric declared in runtime/metric_defs.py, and
                      whose name has a backticked row in
                      docs/observability.md (the metric-docs discipline,
                      applied to alert rules).
thread-attrs          threading.Thread(...) without daemon=True and
                      name=...: an unnamed or non-daemon background
                      thread is undiagnosable in stack dumps and can wedge
                      interpreter shutdown.
parse-error           a file under analysis failed to parse.

Suppressions
------------
Inline, justified at the call site::

    body = pickle.dumps(obj)  # graftlint: allow[hot-pickle] control frames only

An allow comment applies to its own line and the line directly below it
(comment-above style). The shipped baseline file
(ray_tpu/analysis/baseline.txt) carries `rule path:line` entries for
violations accepted tree-wide; it ships empty — prefer inline allows,
which sit next to the code they justify.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

RULES: Dict[str, str] = {
    "hot-pickle": "pickle on a zero-pickle hot-path module",
    "actor-init-blocking": "blocking call inside a remote-class __init__",
    "wire-field-order": "*Msg field numbers out of order or duplicated",
    "wire-field-default": "*Msg field default is not an immutable literal",
    "wire-roundtrip": "*Msg missing from the roundtrip-test registry",
    "event-docs": "event type has no docs/observability.md row",
    "event-undeclared": "emit() with an unregistered event-type literal",
    "metric-def": "metric definition hygiene (name/description/tag_keys)",
    "metric-docs": "metric has no docs/observability.md row",
    "metric-central": "metric constructed outside runtime/metric_defs.py",
    "metric-tags": "metric observed with undeclared tag keys",
    "alert-def": "alert rule on an undeclared series or without a docs row",
    "thread-attrs": "threading.Thread without daemon=True and name=",
    "parse-error": "file failed to parse",
}

_PICKLE_MODULES = {"pickle", "cloudpickle", "_pickle", "cPickle", "dill"}
_PICKLE_FUNCS = {"dumps", "loads", "dump", "load", "Pickler", "Unpickler"}
_RAY_BLOCKING = {"get", "wait"}
_BLOCKING_ATTRS = {"replica_handles", "init_collective_group",
                   "create_collective_group"}
_COLLECTIVE_OPS = {"allreduce", "allgather", "reducescatter", "broadcast",
                   "barrier", "alltoall", "send", "recv",
                   "allreduce_gradients"}
_METRIC_CLASSES = {"Counter", "Gauge", "Histogram"}
_METRIC_OBSERVERS = {"inc", "set", "observe", "bind"}
_ALLOW_RE = re.compile(r"#\s*graftlint:\s*allow\[([a-z\-, ]+)\]")


# Default hot-path module set: the wire paths whose steady state must move
# zero pickled bytes (each has a counter-proof test; the lint keeps new
# call sites out between test runs). Frozen: extending it is a PR-review
# decision, not a call-site decision.
HOT_PATHS: Tuple[str, ...] = (
    "ray_tpu/runtime/rpc.py",
    "ray_tpu/collective/cpu_group.py",
    "ray_tpu/dag/device_channel.py",
    "ray_tpu/llm/disagg.py",
    "ray_tpu/llm/prefix_store.py",
    "ray_tpu/checkpoint/manifest.py",
    "ray_tpu/data/streaming.py",
)


@dataclass
class LintConfig:
    """Repo-relative layout the passes read. `root` is the repository
    root (the directory containing the ray_tpu/ package)."""

    root: str
    package: str = "ray_tpu"
    hot_paths: Tuple[str, ...] = HOT_PATHS
    wire_module: str = "ray_tpu/runtime/wire.py"
    events_module: str = "ray_tpu/runtime/events.py"
    metric_defs_module: str = "ray_tpu/runtime/metric_defs.py"
    alert_defs_module: str = "ray_tpu/runtime/alert_defs.py"
    metrics_module: str = "ray_tpu/util/metrics.py"
    roundtrip_registry: str = "tests/test_wire_schema.py"
    registry_name: str = "WIRE_ROUNDTRIP_REGISTRY"
    docs_observability: str = "docs/observability.md"
    baseline: str = "ray_tpu/analysis/baseline.txt"


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def key(self) -> str:
        return f"{self.rule} {self.path}:{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclass
class LintResult:
    violations: List[Violation] = field(default_factory=list)
    suppressed: int = 0
    baselined: int = 0
    files_scanned: int = 0
    notes: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {"ok": self.ok,
                "violations": [v.to_dict() for v in self.violations],
                "suppressed": self.suppressed, "baselined": self.baselined,
                "files_scanned": self.files_scanned, "notes": self.notes}


class _Module:
    """One parsed file: tree + allow-comment map + import alias tables."""

    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.tree = ast.parse(source)
        # alias -> full dotted target ("md" -> "ray_tpu.runtime.metric_defs",
        # "dumps" -> "pickle.dumps"). Collected over the WHOLE tree: the
        # codebase imports lazily inside functions on purpose.
        self.imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        self.allows: Dict[int, Set[str]] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _ALLOW_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                # The comment covers its own line and the next (so it can
                # sit above a long call).
                self.allows.setdefault(i, set()).update(rules)
                self.allows.setdefault(i + 1, set()).update(rules)

    def allowed(self, rule: str, line: int) -> bool:
        return rule in self.allows.get(line, ())

    def resolves(self, name: str, target: str) -> bool:
        return self.imports.get(name) == target


def _load_modules(cfg: LintConfig) -> Tuple[Dict[str, _Module],
                                            List[Violation]]:
    mods: Dict[str, _Module] = {}
    errors: List[Violation] = []
    pkg_dir = os.path.join(cfg.root, cfg.package)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, cfg.root).replace(os.sep, "/")
            try:
                with open(full, encoding="utf-8") as f:
                    mods[rel] = _Module(rel, f.read())
            except (SyntaxError, UnicodeDecodeError) as e:
                errors.append(Violation(
                    "parse-error", rel, getattr(e, "lineno", 0) or 0,
                    f"failed to parse: {e}"))
    return mods, errors


def _read_text(cfg: LintConfig, rel: str) -> Optional[str]:
    path = os.path.join(cfg.root, rel)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return f.read()


# --------------------------------------------------------------- passes

def _pass_hot_pickle(cfg: LintConfig,
                     mods: Dict[str, _Module]) -> Iterator[Violation]:
    for rel in cfg.hot_paths:
        mi = mods.get(rel)
        if mi is None:
            continue
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = None
            if isinstance(f, ast.Attribute) and f.attr in _PICKLE_FUNCS:
                base = f.value
                if isinstance(base, ast.Name) and (
                        base.id in _PICKLE_MODULES
                        or mi.imports.get(base.id) in _PICKLE_MODULES):
                    hit = f"{base.id}.{f.attr}"
                elif (isinstance(base, ast.Attribute)
                      and base.attr in _PICKLE_MODULES):
                    hit = f"{base.attr}.{f.attr}"  # e.g. rpc.pickle.dumps
            elif isinstance(f, ast.Name):
                full = mi.imports.get(f.id, "")
                mod, _, fn = full.rpartition(".")
                if mod in _PICKLE_MODULES and fn in _PICKLE_FUNCS:
                    hit = full
            if hit:
                yield Violation(
                    "hot-pickle", rel, node.lineno,
                    f"{hit} on a zero-pickle hot path — move the payload "
                    f"to raw/typed frames, or justify with an inline "
                    f"`# graftlint: allow[hot-pickle] <why>`")


def _is_remote_class(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        try:
            text = ast.unparse(dec)
        except Exception:  # pragma: no cover - unparse of exotic nodes
            continue
        if re.search(r"\b(remote|deployment)\b", text):
            return True
    return False


def _blocking_call(mi: _Module, call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        base = f.value
        if f.attr in _RAY_BLOCKING and isinstance(base, ast.Name) and (
                base.id == "ray_tpu"
                or mi.resolves(base.id, "ray_tpu")):
            return f"ray_tpu.{f.attr}()"
        if f.attr in _BLOCKING_ATTRS:
            return f".{f.attr}()"
        if f.attr in _COLLECTIVE_OPS and isinstance(base, ast.Name) and (
                base.id == "collective"
                or mi.resolves(base.id, "ray_tpu.collective")):
            return f"collective.{f.attr}()"
    elif isinstance(f, ast.Name):
        full = mi.imports.get(f.id, "")
        if full in ("ray_tpu.get", "ray_tpu.wait"):
            return f"{full}()"
        if f.id in _BLOCKING_ATTRS:
            return f"{f.id}()"
    return None


def _pass_actor_init(cfg: LintConfig,
                     mods: Dict[str, _Module]) -> Iterator[Violation]:
    for rel, mi in mods.items():
        for node in ast.walk(mi.tree):
            if not (isinstance(node, ast.ClassDef)
                    and _is_remote_class(node)):
                continue
            methods = {m.name: m for m in node.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            init = methods.get("__init__")
            if init is None:
                continue
            # __init__ plus every same-class helper reachable from it via
            # self.<m>() — the deadlock hides one hop down as often as not.
            reachable, queue = {"__init__"}, [init]
            while queue:
                fn = queue.pop()
                for c in ast.walk(fn):
                    if (isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Attribute)
                            and isinstance(c.func.value, ast.Name)
                            and c.func.value.id == "self"
                            and c.func.attr in methods
                            and c.func.attr not in reachable):
                        reachable.add(c.func.attr)
                        queue.append(methods[c.func.attr])
            for name in sorted(reachable):
                for c in ast.walk(methods[name]):
                    if not isinstance(c, ast.Call):
                        continue
                    what = _blocking_call(mi, c)
                    if what:
                        via = ("" if name == "__init__"
                               else f" (via self.{name}(), reached from "
                                    f"__init__)")
                        yield Violation(
                            "actor-init-blocking", rel, c.lineno,
                            f"{what} inside {node.name}.__init__{via}: a "
                            f"remote constructor must not block on the "
                            f"control plane that is constructing it — "
                            f"resolve lazily on first use")


def _msg_classes(tree: ast.Module) -> Iterator[ast.ClassDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name.endswith("Msg") \
                and not node.name.startswith("_"):
            yield node


def _msg_fields(cls: ast.ClassDef):
    """Yield (name, number, default_node, lineno) for Field assignments."""
    for stmt in cls.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Name)
                and stmt.value.func.id == "Field"):
            continue
        call = stmt.value
        number = None
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, int):
            number = call.args[0].value
        default = next((kw.value for kw in call.keywords
                        if kw.arg == "default"), None)
        yield stmt.targets[0].id, number, default, stmt.lineno


def _registry_names(cfg: LintConfig) -> Optional[Set[str]]:
    text = _read_text(cfg, cfg.roundtrip_registry)
    if text is None:
        return None
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            target = node.target.id
        if target == cfg.registry_name \
                and isinstance(getattr(node, "value", None), ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


def _pass_wire(cfg: LintConfig, mods: Dict[str, _Module],
               notes: List[str]) -> Iterator[Violation]:
    mi = mods.get(cfg.wire_module)
    if mi is None:
        return
    registry = _registry_names(cfg)
    if registry is None:
        notes.append(
            f"wire-roundtrip skipped: no {cfg.registry_name} in "
            f"{cfg.roundtrip_registry}")
    for cls in _msg_classes(mi.tree):
        seen: Dict[int, str] = {}
        prev = 0
        for name, number, default, lineno in _msg_fields(cls):
            if number is None:
                yield Violation(
                    "wire-field-order", cfg.wire_module, lineno,
                    f"{cls.name}.{name}: field number must be an int "
                    f"literal (numbers are wire identity)")
                continue
            if number in seen:
                yield Violation(
                    "wire-field-order", cfg.wire_module, lineno,
                    f"{cls.name}.{name}: duplicate field number {number} "
                    f"(already used by {seen[number]})")
            elif number < prev:
                yield Violation(
                    "wire-field-order", cfg.wire_module, lineno,
                    f"{cls.name}.{name}: field number {number} declared "
                    f"after {prev} — keep declaration order ascending so "
                    f"the class reads as the wire schema")
            seen[number] = name
            prev = max(prev, number)
            if default is not None and not (
                    isinstance(default, ast.Constant)
                    or (isinstance(default, ast.UnaryOp)
                        and isinstance(default.operand, ast.Constant))):
                yield Violation(
                    "wire-field-default", cfg.wire_module, lineno,
                    f"{cls.name}.{name}: default must be an immutable "
                    f"literal — a mutable default is shared across every "
                    f"decoded instance")
        if registry is not None and cls.name not in registry:
            yield Violation(
                "wire-roundtrip", cfg.wire_module, cls.lineno,
                f"{cls.name} has no entry in {cfg.registry_name} "
                f"({cfg.roundtrip_registry}) — every wire frame must "
                f"round-trip in CI before a peer depends on it")


def _event_types(mi: _Module) -> Tuple[Dict[str, Tuple[str, int]],
                                       List[str]]:
    """(constant name -> (string value, line), ordered type values)."""
    consts: Dict[str, Tuple[str, int]] = {}
    ordered: List[str] = []
    for node in mi.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
            if isinstance(value, ast.Constant) \
                    and isinstance(value.value, str):
                consts[target] = (value.value, node.lineno)
            elif target == "EVENT_TYPES" \
                    and isinstance(value, (ast.Tuple, ast.List)):
                for elt in value.elts:
                    if isinstance(elt, ast.Name) and elt.id in consts:
                        ordered.append(consts[elt.id][0])
                    elif isinstance(elt, ast.Constant):
                        ordered.append(elt.value)
    return consts, ordered


def _pass_events(cfg: LintConfig, mods: Dict[str, _Module],
                 notes: List[str]) -> Iterator[Violation]:
    mi = mods.get(cfg.events_module)
    if mi is None:
        return
    consts, types = _event_types(mi)
    docs = _read_text(cfg, cfg.docs_observability)
    if docs is None:
        notes.append(f"event-docs skipped: {cfg.docs_observability} "
                     f"not found")
    else:
        for value in types:
            if f"`{value}`" not in docs:
                line = next((ln for v, ln in consts.values() if v == value),
                            0)
                yield Violation(
                    "event-docs", cfg.events_module, line,
                    f"event type {value} has no row in "
                    f"{cfg.docs_observability} — document who emits it "
                    f"and when before shipping it")
    known = set(types)
    events_target = cfg.events_module[:-3].replace("/", ".")
    for rel, m in mods.items():
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            f = node.func
            is_emit = False
            if isinstance(f, ast.Attribute) \
                    and f.attr in ("emit", "make_event") \
                    and isinstance(f.value, ast.Name) \
                    and m.imports.get(f.value.id) == events_target:
                is_emit = True
            elif isinstance(f, ast.Name) and m.imports.get(f.id) in (
                    f"{events_target}.emit",
                    f"{events_target}.make_event"):
                is_emit = True
            if is_emit and node.args[0].value not in known:
                yield Violation(
                    "event-undeclared", rel, node.lineno,
                    f"emit({node.args[0].value!r}): not a registered "
                    f"event type — add it to EVENT_TYPES in "
                    f"{cfg.events_module} (and its docs row)")


def _metric_registry(cfg: LintConfig, mods: Dict[str, _Module]
                     ) -> Tuple[Dict[str, Set[str]], List[Violation]]:
    """Parse metric_defs.py: var name -> declared tag keys, plus hygiene
    violations."""
    registry: Dict[str, Set[str]] = {}
    violations: List[Violation] = []
    mi = mods.get(cfg.metric_defs_module)
    if mi is None:
        return registry, violations
    for node in mi.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id in _METRIC_CLASSES):
            continue
        var, call = node.targets[0].id, node.value
        name_arg = call.args[0] if call.args else None
        if not (isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)
                and name_arg.value.startswith("ray_tpu_")):
            violations.append(Violation(
                "metric-def", cfg.metric_defs_module, node.lineno,
                f"{var}: metric name must be a ray_tpu_-prefixed string "
                f"literal"))
        desc = call.args[1] if len(call.args) > 1 else next(
            (kw.value for kw in call.keywords if kw.arg == "description"),
            None)
        if not (isinstance(desc, ast.Constant)
                and isinstance(desc.value, str) and desc.value.strip()):
            violations.append(Violation(
                "metric-def", cfg.metric_defs_module, node.lineno,
                f"{var}: metric needs a non-empty description (the table "
                f"is the documentation)"))
        tags: Set[str] = set()
        tag_kw = next((kw.value for kw in call.keywords
                       if kw.arg == "tag_keys"), None)
        if tag_kw is not None:
            if isinstance(tag_kw, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str) for e in tag_kw.elts):
                tags = {e.value for e in tag_kw.elts}
            else:
                violations.append(Violation(
                    "metric-def", cfg.metric_defs_module, node.lineno,
                    f"{var}: tag_keys must be a literal tuple of strings "
                    f"so the declared tag set is statically checkable"))
        registry[var] = tags
    return registry, violations


def _pass_metric_docs(cfg: LintConfig, mods: Dict[str, _Module],
                      notes: List[str]) -> Iterator[Violation]:
    """Every metric declared in metric_defs.py needs a backticked row in
    docs/observability.md — the event-docs discipline applied to metrics:
    the docs table is the contract for what operators can alert on."""
    mi = mods.get(cfg.metric_defs_module)
    if mi is None:
        return
    docs = _read_text(cfg, cfg.docs_observability)
    if docs is None:
        notes.append(f"metric-docs skipped: {cfg.docs_observability} "
                     f"not found")
        return
    for node in mi.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id in _METRIC_CLASSES):
            continue
        name_arg = node.value.args[0] if node.value.args else None
        if not (isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)):
            continue  # metric-def already flags non-literal names
        if f"`{name_arg.value}`" not in docs:
            yield Violation(
                "metric-docs", cfg.metric_defs_module, node.lineno,
                f"metric {name_arg.value} has no row in "
                f"{cfg.docs_observability} — document what it measures "
                f"and when it moves before shipping it")


def _declared_metric_names(cfg: LintConfig,
                           mods: Dict[str, _Module]) -> Set[str]:
    """Metric NAME strings (not var names) declared in metric_defs.py."""
    names: Set[str] = set()
    mi = mods.get(cfg.metric_defs_module)
    if mi is None:
        return names
    for node in mi.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id in _METRIC_CLASSES
                and node.value.args
                and isinstance(node.value.args[0], ast.Constant)
                and isinstance(node.value.args[0].value, str)):
            names.add(node.value.args[0].value)
    return names


def _pass_alert_defs(cfg: LintConfig, mods: Dict[str, _Module],
                     notes: List[str]) -> Iterator[Violation]:
    """ALERT_RULES hygiene: literal rules only, each referencing a series
    declared in metric_defs.py, each with a backticked docs row — an alert
    over a series nobody emits would be dead weight that never fires, and
    an undocumented rule is one an operator cannot interpret at 3am."""
    mi = mods.get(cfg.alert_defs_module)
    if mi is None:
        return
    declared = _declared_metric_names(cfg, mods)
    docs = _read_text(cfg, cfg.docs_observability)
    if docs is None:
        notes.append(f"alert-def docs check skipped: "
                     f"{cfg.docs_observability} not found")
    rules_node = next(
        (node.value for node in mi.tree.body
         if isinstance(node, ast.Assign) and len(node.targets) == 1
         and isinstance(node.targets[0], ast.Name)
         and node.targets[0].id == "ALERT_RULES"), None)
    if not isinstance(rules_node, (ast.List, ast.Tuple)):
        yield Violation(
            "alert-def", cfg.alert_defs_module, 1,
            "ALERT_RULES must be a literal list of dicts (the lint and "
            "the GCS evaluator both read it as data)")
        return
    for elt in rules_node.elts:
        if not isinstance(elt, ast.Dict):
            yield Violation(
                "alert-def", cfg.alert_defs_module, elt.lineno,
                "alert rule must be a literal dict — no computed rules")
            continue
        fields: Dict[str, object] = {}
        for k, v in zip(elt.keys, elt.values):
            if isinstance(k, ast.Constant) and isinstance(v, ast.Constant):
                fields[k.value] = v.value
        name = fields.get("name")
        series = fields.get("series")
        if not isinstance(name, str) or not name:
            yield Violation(
                "alert-def", cfg.alert_defs_module, elt.lineno,
                "alert rule needs a literal string `name` (the event "
                "signature and docs-row key)")
            continue
        if not isinstance(series, str) or series not in declared:
            yield Violation(
                "alert-def", cfg.alert_defs_module, elt.lineno,
                f"alert rule {name}: series {series!r} is not declared "
                f"in {cfg.metric_defs_module} — alerts may only watch "
                f"registered metrics")
        if docs is not None and f"`{name}`" not in docs:
            yield Violation(
                "alert-def", cfg.alert_defs_module, elt.lineno,
                f"alert rule {name} has no row in "
                f"{cfg.docs_observability} — document what it watches "
                f"and what an operator should do before shipping it")


def _pass_metrics(cfg: LintConfig,
                  mods: Dict[str, _Module]) -> Iterator[Violation]:
    registry, def_violations = _metric_registry(cfg, mods)
    yield from def_violations
    defs_target = cfg.metric_defs_module[:-3].replace("/", ".")
    metrics_target = cfg.metrics_module[:-3].replace("/", ".")
    for rel, mi in mods.items():
        if rel in (cfg.metric_defs_module, cfg.metrics_module):
            continue
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # Centralization: runtime metrics are defined once, in the
            # table — a Counter() constructed elsewhere escapes the
            # registry lint and the docs.
            constructed = None
            if isinstance(f, ast.Name) and mi.imports.get(f.id) in {
                    f"{metrics_target}.{c}" for c in _METRIC_CLASSES}:
                constructed = f.id
            elif isinstance(f, ast.Attribute) \
                    and f.attr in _METRIC_CLASSES \
                    and isinstance(f.value, ast.Name) \
                    and mi.imports.get(f.value.id) == metrics_target:
                constructed = f.attr
            if constructed:
                yield Violation(
                    "metric-central", rel, node.lineno,
                    f"{constructed}(...) outside "
                    f"{cfg.metric_defs_module}: define runtime metrics in "
                    f"the central table (import and bind them here)")
                continue
            # Tag discipline at observation sites, statically: only
            # literal dict tags are checkable; variables are covered by
            # the runtime ValueError in util/metrics.py.
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _METRIC_OBSERVERS):
                continue
            base = f.value
            metric = None
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and mi.imports.get(base.value.id) == defs_target:
                metric = base.attr
            elif isinstance(base, ast.Name) and mi.imports.get(
                    base.id, "").startswith(defs_target + "."):
                metric = mi.imports[base.id].rsplit(".", 1)[1]
            if metric not in registry:
                continue
            tags_expr = next((kw.value for kw in node.keywords
                              if kw.arg == "tags"), None)
            if tags_expr is None and f.attr == "bind" and node.args:
                tags_expr = node.args[0]
            if not isinstance(tags_expr, ast.Dict):
                continue
            keys = {k.value for k in tags_expr.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            undeclared = keys - registry[metric]
            if undeclared:
                yield Violation(
                    "metric-tags", rel, node.lineno,
                    f"{metric}.{f.attr}: tag keys {sorted(undeclared)} "
                    f"not declared in its tag_keys "
                    f"(declared: {sorted(registry[metric])})")


def _pass_threads(cfg: LintConfig,
                  mods: Dict[str, _Module]) -> Iterator[Violation]:
    for rel, mi in mods.items():
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_thread = (
                (isinstance(f, ast.Attribute) and f.attr == "Thread"
                 and isinstance(f.value, ast.Name)
                 and (f.value.id == "threading"
                      or mi.resolves(f.value.id, "threading")))
                or (isinstance(f, ast.Name)
                    and mi.resolves(f.id, "threading.Thread")))
            if not is_thread:
                continue
            kwargs = {kw.arg for kw in node.keywords if kw.arg}
            daemon_kw = next((kw.value for kw in node.keywords
                              if kw.arg == "daemon"), None)
            missing = []
            if not (isinstance(daemon_kw, ast.Constant)
                    and daemon_kw.value is True):
                missing.append("daemon=True")
            if "name" not in kwargs:
                missing.append("name=")
            if missing:
                yield Violation(
                    "thread-attrs", rel, node.lineno,
                    f"threading.Thread missing {' and '.join(missing)}: "
                    f"unnamed threads are opaque in `scripts stack` dumps "
                    f"and non-daemon background threads wedge shutdown")


# --------------------------------------------------------------- driver

def _load_baseline(cfg: LintConfig,
                   path: Optional[str]) -> Set[str]:
    baseline = path or os.path.join(cfg.root, cfg.baseline)
    entries: Set[str] = set()
    if not os.path.exists(baseline):
        return entries
    with open(baseline, encoding="utf-8") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                # "rule path:line" (exact) or "rule path" (whole file).
                entries.add(line)
    return entries


def default_root() -> str:
    """Repository root: the directory containing the ray_tpu package."""
    import ray_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))


def run(root: Optional[str] = None,
        rules: Optional[Iterable[str]] = None,
        baseline_path: Optional[str] = None,
        config: Optional[LintConfig] = None) -> LintResult:
    cfg = config or LintConfig(root=root or default_root())
    wanted = set(rules) if rules else None
    unknown = (wanted or set()) - set(RULES)
    if unknown:
        raise ValueError(f"unknown rules: {sorted(unknown)} "
                         f"(known: {sorted(RULES)})")
    result = LintResult()
    mods, parse_errors = _load_modules(cfg)
    result.files_scanned = len(mods)
    raw: List[Violation] = list(parse_errors)
    raw.extend(_pass_hot_pickle(cfg, mods))
    raw.extend(_pass_actor_init(cfg, mods))
    raw.extend(_pass_wire(cfg, mods, result.notes))
    raw.extend(_pass_events(cfg, mods, result.notes))
    raw.extend(_pass_metric_docs(cfg, mods, result.notes))
    raw.extend(_pass_alert_defs(cfg, mods, result.notes))
    raw.extend(_pass_metrics(cfg, mods))
    raw.extend(_pass_threads(cfg, mods))
    baseline = _load_baseline(cfg, baseline_path)
    for v in raw:
        if wanted is not None and v.rule not in wanted:
            continue
        mi = mods.get(v.path)
        if mi is not None and mi.allowed(v.rule, v.line):
            result.suppressed += 1
            continue
        if v.key() in baseline or f"{v.rule} {v.path}" in baseline:
            result.baselined += 1
            continue
        result.violations.append(v)
    result.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return result


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="project-invariant static analysis over ray_tpu/")
    parser.add_argument("--root", default=None,
                        help="repository root (default: the directory "
                             "containing the installed ray_tpu package)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--baseline", default=None,
                        help="baseline file overriding the shipped "
                             "ray_tpu/analysis/baseline.txt")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="RULE",
                        help="run only this rule id (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    args = parser.parse_args(argv)
    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule:22s} {desc}")
        return 0
    try:
        result = run(root=args.root, rules=args.rule,
                     baseline_path=args.baseline)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
    else:
        for v in result.violations:
            print(v.render())
        tail = (f"{len(result.violations)} violation(s), "
                f"{result.suppressed} allowed inline, "
                f"{result.baselined} baselined, "
                f"{result.files_scanned} files")
        for note in result.notes:
            print(f"note: {note}")
        print(tail if result.violations else f"clean: {tail}")
    return 0 if result.ok else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
