"""Runtime sanitizers: the dynamic halves of the graftlint invariants.

:class:`PickleSanitizer` proves the zero-pickle property at test time the
way tsan proves data-race freedom: hook the primitive, attribute every
call to its call site, and let the test assert over a scoped window. It
subsumes the old per-test plumbing of ``serialization.counter_snapshot``
/ ``counter_delta`` pairs — one fixture, and every event comes with the
``file:line`` that pickled, so a failing zero-pickle test names the
regressing call site instead of printing a bare counter delta.

:class:`LockOrderSanitizer` wraps ``threading.Lock`` for the duration of
a test, records which locks each thread holds while acquiring others,
and reports lock-order inversions (cycles in the cross-thread
acquisition graph) with BOTH acquisition stacks. The router control
loop, checkpoint persister, and collective tx threads all hold locks
concurrently; an inversion between them is a deadlock that strikes under
load, not under test — unless the order graph itself is checked.

Both sanitizers patch process-global primitives, so they are scoped:
install on ``__enter__``, restore on ``__exit__``, refcounted so nested
windows (e.g. a test window around an actor that opens its own) compose.
"""

from __future__ import annotations

import itertools
import os
import pickle
import sys
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.analysis.graftlint import HOT_PATHS, _ALLOW_RE
from ray_tpu.core import serialization as _ser

_THIS_FILE = os.path.abspath(__file__)

# Lines carrying (or directly under) an inline `# graftlint:
# allow[hot-pickle]` comment, per absolute source path. The sanitizer
# honors the SAME waivers as the static lint: a justified control-frame
# codec on a hot-path module is not a hot event at runtime either.
_allow_cache: Dict[str, frozenset] = {}


def _hot_allowed_lines(abs_path: str) -> frozenset:
    cached = _allow_cache.get(abs_path)
    if cached is None:
        lines = set()
        try:
            with open(abs_path, encoding="utf-8") as fh:
                for i, text in enumerate(fh, start=1):
                    m = _ALLOW_RE.search(text)
                    if m and "hot-pickle" in m.group(1):
                        lines.update((i, i + 1))
        except OSError:
            pass
        cached = frozenset(lines)
        _allow_cache[abs_path] = cached
    return cached


def _rel_site(filename: str) -> str:
    """Normalize an absolute frame filename to a repo-relative path when
    it lives under the ray_tpu package (so hot-path matching and test
    assertions are location-independent)."""
    norm = filename.replace(os.sep, "/")
    idx = norm.rfind("/ray_tpu/")
    if idx >= 0:
        return norm[idx + 1:]
    return norm


def _is_hot(site: str) -> bool:
    return site in HOT_PATHS


@dataclass
class PickleEvent:
    op: str        # dumps | loads | dump | load
    site: str      # repo-relative file of the innermost ray_tpu frame
    line: int
    function: str
    hot: bool

    def render(self) -> str:
        flag = " [HOT PATH]" if self.hot else ""
        return f"pickle.{self.op} at {self.site}:{self.line} " \
               f"(in {self.function}){flag}"

    def to_dict(self) -> dict:
        return {"op": self.op, "site": self.site, "line": self.line,
                "function": self.function, "hot": self.hot}


# ---------------------------------------------------------- pickle hook
#
# One process-global patch shared by every open window. pickle.dumps &
# co. are rebound on the pickle MODULE, so call sites that do
# `import pickle; pickle.dumps(...)` (the codebase idiom) route through
# the hook; cloudpickle is hooked the same way when present. The patch
# is installed only while at least one window is open.

_patch_lock = threading.Lock()
_active_windows: List["Window"] = []
_originals: Dict[Tuple[Any, str], Any] = {}


def _call_site() -> Tuple[str, str, int, str]:
    """(abs_path, rel_site, line, function) of the innermost ray_tpu
    frame below the hook (falling back to the innermost non-pickle frame,
    e.g. a test function)."""
    f = sys._getframe(2)
    first = None
    while f is not None:
        fn = f.f_code.co_filename
        base = os.path.basename(fn)
        if os.path.abspath(fn) != _THIS_FILE and "pickle" not in base:
            if first is None:
                first = f
            rel = _rel_site(fn)
            if rel.startswith("ray_tpu/"):
                return (os.path.abspath(fn), rel, f.f_lineno,
                        f.f_code.co_name)
        f = f.f_back
    if first is not None:
        fn = first.f_code.co_filename
        return (os.path.abspath(fn), _rel_site(fn), first.f_lineno,
                first.f_code.co_name)
    return "<unknown>", "<unknown>", 0, "<unknown>"


def _record(op: str) -> None:
    abs_path, site, line, func = _call_site()
    hot = (_is_hot(site)
           and line not in _hot_allowed_lines(abs_path))
    event = PickleEvent(op=op, site=site, line=line, function=func,
                        hot=hot)
    for w in list(_active_windows):
        w.events.append(event)


def _make_hook(op: str, original):
    def hook(*args, **kwargs):
        _record(op)
        return original(*args, **kwargs)

    hook.__name__ = f"_sanitized_{op}"
    return hook


def _install() -> None:
    targets: List[Tuple[Any, str]] = [(pickle, n)
                                      for n in ("dumps", "loads",
                                                "dump", "load")]
    cp = sys.modules.get("cloudpickle")
    if cp is not None:
        targets.extend((cp, n) for n in ("dumps", "dump"))
    for mod, name in targets:
        original = getattr(mod, name)
        _originals[(mod, name)] = original
        setattr(mod, name, _make_hook(name, original))


def _uninstall() -> None:
    for (mod, name), original in _originals.items():
        setattr(mod, name, original)
    _originals.clear()


class Window:
    """A scoped pickle-observation window.

    Usable as a pytest-fixture product (``pickle_sanitizer.window()``)
    or standalone inside a remote actor (``with pickle_window() as w``).
    Events and counter deltas remain readable after ``__exit__``;
    :meth:`summary` returns a plain-dict form that crosses the actor
    boundary without dragging the sanitizer along.
    """

    def __init__(self) -> None:
        self.events: List[PickleEvent] = []
        self._since: Dict[str, int] = {}
        self._counters: Optional[Dict[str, int]] = None

    def __enter__(self) -> "Window":
        self._since = _ser.counter_snapshot()
        with _patch_lock:
            if not _active_windows:
                _install()
            _active_windows.append(self)
        return self

    def __exit__(self, *exc) -> None:
        self._counters = _ser.counter_delta(self._since)
        with _patch_lock:
            if self in _active_windows:
                _active_windows.remove(self)
            if not _active_windows:
                _uninstall()

    @property
    def counters(self) -> Dict[str, int]:
        """Serialization-counter delta over the window (live while the
        window is open, frozen at exit)."""
        if self._counters is not None:
            return self._counters
        return _ser.counter_delta(self._since)

    @property
    def hot_events(self) -> List[PickleEvent]:
        return [e for e in self.events if e.hot]

    def assert_zero_pickle(self) -> None:
        """The steady-state invariant: no slow-path value pickling and no
        pickle call attributed to a hot-path module inside the window."""
        c = self.counters
        problems = []
        if c.get("pickle", 0):
            problems.append(
                f"{c['pickle']} slow-path serialize() pickle(s)")
        if c.get("deserialize_pickle", 0):
            problems.append(
                f"{c['deserialize_pickle']} slow-path deserialize(s)")
        hot = self.hot_events
        if hot:
            sites = "\n  ".join(e.render() for e in hot)
            problems.append(f"{len(hot)} hot-path pickle call(s):\n  "
                            f"{sites}")
        assert not problems, (
            "zero-pickle window violated: " + "; ".join(problems))

    def summary(self) -> dict:
        """Plain-dict snapshot, safe to return across an actor boundary."""
        return {
            "counters": dict(self.counters),
            "events": [e.to_dict() for e in self.events],
            "hot_sites": sorted({f"{e.site}:{e.line}"
                                 for e in self.events if e.hot}),
            "pickle_calls": len(self.events),
        }


def pickle_window() -> Window:
    """Standalone window — importable inside a remote actor method."""
    return Window()


class PickleSanitizer:
    """Fixture-facing handle: mints windows and keeps them for teardown
    reporting. One sanitizer per test; windows may nest or repeat."""

    def __init__(self) -> None:
        self.windows: List[Window] = []

    def window(self) -> Window:
        w = Window()
        self.windows.append(w)
        return w

    def close(self) -> None:
        # Belt and braces: a test that leaks an open window must not
        # leave pickle patched for the rest of the session.
        with _patch_lock:
            for w in self.windows:
                if w in _active_windows:
                    _active_windows.remove(w)
            if not _active_windows:
                _uninstall()


# ------------------------------------------------------ lock-order hook

def _creation_site() -> str:
    f = sys._getframe(2)
    while f is not None:
        if os.path.abspath(f.f_code.co_filename) != _THIS_FILE:
            return f"{_rel_site(f.f_code.co_filename)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


@dataclass
class _Edge:
    """First observed held->acquired ordering, with both stacks."""

    src: str            # name (creation site) of the held lock
    dst: str            # name of the lock being acquired
    thread: str
    src_stack: List[str]   # where the held lock was acquired
    dst_stack: List[str]   # where the new lock is being acquired


@dataclass
class LockInversion:
    cycle: List[str]                  # lock names forming the cycle
    edges: List[_Edge] = field(default_factory=list)

    def render(self) -> str:
        lines = ["lock-order inversion: "
                 + " -> ".join(self.cycle + [self.cycle[0]])]
        for e in self.edges:
            lines.append(
                f"  thread {e.thread!r} acquired {e.dst} while holding "
                f"{e.src}:")
            lines.append(f"    {e.src} acquired at:")
            lines.extend(f"      {ln}" for ln in e.src_stack)
            lines.append(f"    {e.dst} acquired at:")
            lines.extend(f"      {ln}" for ln in e.dst_stack)
        return "\n".join(lines)


_lock_seq = itertools.count(1)


class _TrackedLock:
    """Drop-in for the object returned by ``threading.Lock()``."""

    def __init__(self, sanitizer: "LockOrderSanitizer", site: str):
        self._lock = sanitizer._real_lock_factory()
        self._sanitizer = sanitizer
        # Graph nodes are lock INSTANCES, displayed by creation site.
        # Keying by site alone would merge distinct locks born on one
        # line (a, b = Lock(), Lock()) into a single node, turning one
        # thread's nested acquire into a self-edge "cycle".
        self.name = f"{site}#{next(_lock_seq)}"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Record the intent BEFORE blocking: a real deadlock never
        # returns from acquire, and the whole point is to report the
        # ordering that caused it.
        self._sanitizer._on_acquire_attempt(self)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._sanitizer._on_acquired(self)
        return ok

    def release(self) -> None:
        self._sanitizer._on_release(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<TrackedLock {self.name}>"


def _thread_name() -> str:
    """Current thread's name WITHOUT threading.current_thread(): that
    call materializes a _DummyThread for a not-yet-registered thread,
    whose __init__ sets an Event — acquiring a tracked lock and
    re-entering this hook forever. Reading _active directly is what
    faulthandler does for the same reason."""
    ident = threading.get_ident()
    t = threading._active.get(ident)
    return t.name if t is not None else f"thread-{ident}"


def _stack_lines(skip: int = 2) -> List[str]:
    frames = traceback.extract_stack()[:-skip]
    out = []
    for fr in frames:
        fn = _rel_site(fr.filename)
        if os.path.abspath(fr.filename) == _THIS_FILE:
            continue
        out.append(f"{fn}:{fr.lineno} in {fr.name}")
    return out[-8:]  # innermost 8 non-sanitizer frames


class LockOrderSanitizer:
    """Scoped ``threading.Lock`` wrapper that builds the cross-thread
    lock-order graph and reports cycles.

    Usage (typically via the ``lock_sanitizer`` fixture)::

        with LockOrderSanitizer() as san:
            ... run the threads under test ...
        san.assert_no_inversions()

    Locks created while the sanitizer is installed are tracked; each
    acquisition while another tracked lock is held adds a held->acquired
    edge tagged with the acquiring thread and both acquisition stacks.
    A cycle in the edge graph is an ordering that can deadlock under the
    right interleaving — reported even if this run got lucky.
    """

    def __init__(self) -> None:
        self._real_lock_factory = None
        self._tls = threading.local()
        # (src_name, dst_name) -> first observed _Edge. Mutated under
        # _graph_lock: a REAL lock allocated before patching.
        self._edges: Dict[Tuple[str, str], _Edge] = {}
        self._graph_lock = threading.Lock()
        self._installed = False

    # -- patch lifecycle -------------------------------------------------

    def __enter__(self) -> "LockOrderSanitizer":
        self._real_lock_factory = threading.Lock
        sanitizer = self

        def _tracked_lock_factory():
            return _TrackedLock(sanitizer, _creation_site())

        threading.Lock = _tracked_lock_factory
        self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            threading.Lock = self._real_lock_factory
            self._installed = False

    # -- acquisition tracking --------------------------------------------

    def _held(self) -> List[Tuple[_TrackedLock, List[str]]]:
        if not hasattr(self._tls, "held"):
            self._tls.held = []
        return self._tls.held

    def _on_acquire_attempt(self, lock: _TrackedLock) -> None:
        stack = _stack_lines(skip=3)
        thread = _thread_name()
        for held, held_stack in self._held():
            if held is lock:
                continue
            key = (held.name, lock.name)
            with self._graph_lock:
                if key not in self._edges:
                    self._edges[key] = _Edge(
                        src=held.name, dst=lock.name, thread=thread,
                        src_stack=held_stack, dst_stack=stack)

    def _on_acquired(self, lock: _TrackedLock) -> None:
        self._held().append((lock, _stack_lines(skip=3)))

    def _on_release(self, lock: _TrackedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                del held[i]
                return

    # -- analysis --------------------------------------------------------

    def inversions(self) -> List[LockInversion]:
        with self._graph_lock:
            edges = dict(self._edges)
        graph: Dict[str, List[str]] = {}
        for (src, dst) in edges:
            graph.setdefault(src, []).append(dst)
            graph.setdefault(dst, [])
        # DFS back-edge detection; each distinct cycle reported once.
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        path: List[str] = []
        cycles: List[List[str]] = []
        seen: set = set()

        def visit(n: str) -> None:
            color[n] = GREY
            path.append(n)
            for m in graph[n]:
                if color[m] == GREY:
                    cycle = path[path.index(m):]
                    key = frozenset(cycle)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(cycle))
                elif color[m] == WHITE:
                    visit(m)
            path.pop()
            color[n] = BLACK

        for n in sorted(graph):
            if color[n] == WHITE:
                visit(n)
        out = []
        for cycle in cycles:
            inv = LockInversion(cycle=cycle)
            for i, src in enumerate(cycle):
                dst = cycle[(i + 1) % len(cycle)]
                if (src, dst) in edges:
                    inv.edges.append(edges[(src, dst)])
            out.append(inv)
        return out

    def report(self) -> str:
        invs = self.inversions()
        if not invs:
            return "lock-order: no inversions detected"
        return "\n\n".join(inv.render() for inv in invs)

    def assert_no_inversions(self) -> None:
        invs = self.inversions()
        assert not invs, self.report()
