"""CLI: cluster lifecycle + introspection.

Reference analog: python/ray/scripts/scripts.py (`ray start/stop/status/
memory/...`, registration :2625-2667). Subcommands:

    python -m ray_tpu.scripts start --head [--num-cpus N] [--num-tpus N]
    python -m ray_tpu.scripts start --address HOST:PORT  (join as a node)
    python -m ray_tpu.scripts status --address HOST:PORT
    python -m ray_tpu.scripts list nodes|actors|pgs|jobs --address ...
    python -m ray_tpu.scripts stop --address HOST:PORT
"""

from __future__ import annotations

import argparse
import json
import sys


def _connect(address: str):
    import ray_tpu

    ray_tpu.init(address=address)


def cmd_start(args):
    from ray_tpu.runtime import node as node_mod
    from ray_tpu.runtime import resources as resources_mod

    if args.head:
        session = node_mod.new_session_dir()
        gcs_proc, gcs_addr = node_mod.start_gcs(session)
        res = resources_mod.node_resources(args.num_cpus, args.num_tpus)
        labels = resources_mod.tpu_slice_labels()
        _, info = node_mod.start_raylet(session, gcs_addr, res, labels,
                                       args.object_store_memory, is_head=True)
        print(f"head started; GCS at {gcs_addr[0]}:{gcs_addr[1]}")
        print(f"  session dir: {session}")
        print(f"  connect with: ray_tpu.init(address='{gcs_addr[0]}:{gcs_addr[1]}')")
    else:
        if not args.address:
            sys.exit("--address required to join an existing cluster")
        host, port = args.address.rsplit(":", 1)
        session = node_mod.new_session_dir()
        res = resources_mod.node_resources(args.num_cpus, args.num_tpus)
        labels = resources_mod.tpu_slice_labels()
        _, info = node_mod.start_raylet(session, (host, int(port)), res, labels,
                                       args.object_store_memory)
        print(f"node {info['node_id'][:12]} joined {args.address}")


def cmd_status(args):
    from ray_tpu.state.api import summary

    _connect(args.address)
    print(json.dumps(summary(), indent=2, default=str))


def cmd_list(args):
    from ray_tpu.state import api

    _connect(args.address)
    fetch = {"nodes": api.list_nodes, "actors": api.list_actors,
             "pgs": api.list_placement_groups, "jobs": api.list_jobs}[args.what]
    print(json.dumps(fetch(), indent=2, default=str))


def cmd_stop(args):
    import ray_tpu

    _connect(args.address)
    core = ray_tpu.core.worker.global_worker()
    core.io.run(core.gcs.call("shutdown_cluster", timeout=10))
    print("cluster shutdown requested")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--object-store-memory", type=int, default=2 << 30)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list")
    p.add_argument("what", choices=["nodes", "actors", "pgs", "jobs"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("stop")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_stop)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
