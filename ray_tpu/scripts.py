"""CLI: cluster lifecycle + introspection.

Reference analog: python/ray/scripts/scripts.py (`ray start/stop/status/
memory/...`, registration :2625-2667). Subcommands:

    python -m ray_tpu.scripts start --head [--num-cpus N] [--num-tpus N]
    python -m ray_tpu.scripts start --address HOST:PORT  (join as a node)
    python -m ray_tpu.scripts status --address HOST:PORT
    python -m ray_tpu.scripts list nodes|actors|pgs|jobs --address ...
    python -m ray_tpu.scripts stop --address HOST:PORT
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _connect(address: str):
    import ray_tpu

    ray_tpu.init(address=address, ignore_reinit_error=True)


def cmd_start(args):
    from ray_tpu.runtime import node as node_mod
    from ray_tpu.runtime import resources as resources_mod

    if args.head:
        session = node_mod.new_session_dir()
        gcs_proc, gcs_addr = node_mod.start_gcs(session)
        res = resources_mod.node_resources(args.num_cpus, args.num_tpus)
        labels = resources_mod.tpu_slice_labels()
        _, info = node_mod.start_raylet(session, gcs_addr, res, labels,
                                       args.object_store_memory, is_head=True)
        print(f"head started; GCS at {gcs_addr[0]}:{gcs_addr[1]}")
        print(f"  session dir: {session}")
        print(f"  connect with: ray_tpu.init(address='{gcs_addr[0]}:{gcs_addr[1]}')")
        if not args.no_dashboard:
            try:
                _, url = node_mod.start_dashboard(
                    session, gcs_addr, port=args.dashboard_port)
                print(f"  dashboard: {url}")
            except Exception as e:
                print(f"  dashboard failed to start: {e}")
    else:
        if not args.address:
            sys.exit("--address required to join an existing cluster")
        host, port = args.address.rsplit(":", 1)
        # Resolve the joined cluster's token by its address before the
        # raylet (a child inheriting our env) first dials the GCS.
        from ray_tpu.runtime import rpc as rpc_mod

        if rpc_mod.load_token_for_address(host, int(port)):
            os.environ["RAY_TPU_AUTH_TOKEN"] = (
                rpc_mod.get_session_token().hex())
        session = node_mod.new_session_dir()
        res = resources_mod.node_resources(args.num_cpus, args.num_tpus)
        labels = resources_mod.tpu_slice_labels()
        _, info = node_mod.start_raylet(session, (host, int(port)), res, labels,
                                       args.object_store_memory)
        print(f"node {info['node_id'][:12]} joined {args.address}")


def cmd_status(args):
    from ray_tpu.state.api import summary

    _connect(args.address)
    print(json.dumps(summary(), indent=2, default=str))


def cmd_list(args):
    from ray_tpu.state import api

    _connect(args.address)
    fetch = {"nodes": api.list_nodes, "actors": api.list_actors,
             "pgs": api.list_placement_groups, "jobs": api.list_jobs,
             "tasks": api.list_tasks, "objects": api.list_objects}[args.what]
    print(json.dumps(fetch(), indent=2, default=str))


def cmd_memory(args):
    """Per-node object store usage + owned-object summary (the `ray memory`
    analog: where object bytes live across the cluster). With --cluster,
    fans the owner-scoped object table out to every worker and aggregates
    by owner/size/spill state."""
    from ray_tpu.state import api

    _connect(args.address)
    out = {"nodes": [], "objects": []}
    for s in api.node_stats():
        out["nodes"].append({
            "node_id": s.get("node_id"),
            "store_bytes_used": s.get("object_store_used"),
            "store_capacity": s.get("object_store_capacity"),
            "spilled_bytes": s.get("spilled_bytes"),
            "num_workers": s.get("num_workers"),
            "num_pending_leases": s.get("num_pending_leases"),
        })
    try:
        if args.cluster:
            out["summary"] = api.summarize_objects(limit=args.limit)
            out["objects"] = api.list_cluster_objects(limit=args.limit)
        else:
            out["objects"] = api.list_objects(limit=args.limit)
        out["total_objects"] = len(out["objects"])
    except Exception as e:  # objects view is best-effort
        out["objects_error"] = repr(e)
    print(json.dumps(out, indent=2, default=str))


def cmd_stack(args):
    """Annotated stack dump (`ray stack` analog): every thread of every
    process, with what it is blocked on (object get + owner, collective
    op, channel read) and the task/actor it runs. Without --cluster,
    dumps only this process."""
    from ray_tpu.utils import debug

    if args.cluster:
        if not args.address:
            sys.exit("--cluster requires --address")
        from ray_tpu.state import api

        _connect(args.address)
        procs = api.dump_cluster_stacks()
    else:
        procs = [debug.render_stacks("local")]
    if args.json:
        print(json.dumps(procs, indent=2, default=str))
    else:
        print(debug.format_stacks(procs))
    if args.wait_graph:
        if not args.address:
            sys.exit("--wait-graph requires --address")
        from ray_tpu.state import api as api_mod

        _connect(args.address)
        print(json.dumps(api_mod.wait_graph(), indent=2, default=str))


def cmd_drain(args):
    """Drain a node (DrainNode analog, node_manager.proto).

    With --deadline N the node enters the two-phase DRAINING state: it
    stays alive for N seconds while the scheduler stops leasing onto it,
    its raylet migrates primary object copies to peers, and drain-aware
    consumers checkpoint/re-form; at the deadline the GCS kills it with
    the preempted marker. --deadline 0 (default) is the legacy immediate
    drain: marked dead now, reactive recovery everywhere."""
    from ray_tpu.core import worker as worker_mod

    _connect(args.address)
    core = worker_mod.global_worker()
    node_id = bytes.fromhex(args.node_id)
    reply = core.io.run(core.gcs.call(
        "drain_node", node_id=node_id, reason=args.reason,
        deadline_s=args.deadline))
    print(json.dumps({"drained": args.node_id,
                      "draining": bool(reply.get("draining")),
                      "deadline": reply.get("deadline")}))


def cmd_stop(args):
    import ray_tpu

    _connect(args.address)
    core = ray_tpu.core.worker.global_worker()
    core.io.run(core.gcs.call("shutdown_cluster", timeout=10))
    print("cluster shutdown requested")


def _dashboard_url(address: str) -> str:
    """Resolve the dashboard URL from the GCS KV (set at startup)."""
    import ray_tpu

    _connect(address)
    url = ray_tpu.get_runtime_context().dashboard_url
    if url is None:
        sys.exit("no dashboard registered for this cluster")
    return url


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient, JobStatus

    client = JobSubmissionClient(
        args.dashboard or _dashboard_url(args.address))
    if args.job_cmd == "submit":
        job_id = client.submit_job(entrypoint=" ".join(args.entrypoint))
        print(job_id)
        if args.wait:
            status = client.wait_until_status(job_id)
            print(client.get_job_logs(job_id), end="")
            sys.exit(0 if status == JobStatus.SUCCEEDED else 1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id), end="")
    elif args.job_cmd == "stop":
        client.stop_job(args.job_id)
        print("stopped")
    elif args.job_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2, default=str))


def cmd_timeline(args):
    from ray_tpu.util import tracing

    if args.cluster:
        if not args.address:
            sys.exit("--cluster requires --address")
        from ray_tpu.state import api

        _connect(args.address)
        groups = api.dump_cluster_spans()
        events = tracing.merge_spans(groups)
        with open(args.output, "w") as f:
            json.dump({"traceEvents": events}, f)
        nspans = sum(len(spans) for _, spans in groups)
        print(f"wrote {nspans} spans from {len(groups)} process(es) to "
              f"{args.output} (open in chrome://tracing)")
        return
    tracing.dump_chrome_trace(args.output)
    print(f"wrote {len(tracing.get_spans())} spans to {args.output} "
          "(open in chrome://tracing)")


def cmd_request(args):
    """Stitched per-request serving trace: every span any process recorded
    for one request id — router admission, queueing, prefill, disagg KV
    handoff, decode, failover replay, migration pause — ordered by start
    time. The trace id derives from the request id alone, so this works
    after the fact with nothing but the rid."""
    from ray_tpu.state import api
    from ray_tpu.util import tracing

    if args.cluster:
        if not args.address:
            sys.exit("--cluster requires --address")
        _connect(args.address)
    trace = api.request_trace(args.request_id, cluster=args.cluster)
    spans = trace["spans"]
    if not spans:
        print(f"no spans recorded for request {args.request_id} "
              f"(trace id {trace['trace_id']})")
        return
    if args.chrome:
        groups = {}
        for s in spans:
            groups.setdefault(s.get("process", "?"), []).append(s)
        events = tracing.merge_spans(sorted(groups.items()))
        with open(args.chrome, "w") as f:
            json.dump({"traceEvents": events}, f)
        print(f"wrote {len(spans)} spans to {args.chrome} "
              "(open in chrome://tracing)")
    t0 = min(s["ts"] for s in spans)
    print(f"request {args.request_id}  trace {trace['trace_id']}  "
          f"{len(spans)} span(s)")
    print(f"  {'offset':>12}  {'duration':>12}  span")
    for s in spans:
        off_ms = (s["ts"] - t0) / 1e3
        dur_ms = s.get("dur", 0.0) / 1e3
        extra = {k: v for k, v in (s.get("args") or {}).items()
                 if k not in ("trace_id", "span_id", "parent_span_id",
                              "request_id")}
        attrs = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
        print(f"  {off_ms:>10.3f}ms  {dur_ms:>10.3f}ms  "
              f"{s['name']:<20} [{s.get('process', '?')}]"
              + (f"  {attrs}" if attrs else ""))


def cmd_events(args):
    """Typed cluster events, newest first (`ray list cluster-events`
    analog; see ray_tpu/runtime/events.py for the record shape)."""
    from ray_tpu.state import api

    _connect(args.address)
    events = api.list_cluster_events(event_type=args.type,
                                     severity=args.severity,
                                     source=args.source, limit=args.limit)
    print(json.dumps(events, indent=2, default=str))


def cmd_metrics(args):
    """Windowed queries over the GCS metric-history rings: the aggregate
    value (rate / delta / mean / quantile-over-window), the per-node
    split, and a text sparkline per reporter series. The same data backs
    `state.metrics_history()` and the dashboard's `/api/metrics/history`."""
    from ray_tpu.state import api

    _connect(args.address)
    tags = dict(kv.split("=", 1) for kv in args.tag or ())
    agg = "rate" if args.rate else args.agg
    out = api.metrics_history(args.series, tags=tags or None,
                              window_s=args.window, agg=agg)
    if args.json:
        print(json.dumps(out, indent=2, default=str))
        return
    value = out.get("value")
    shown = out.get("agg") or "auto"
    print(f"{args.series}  window={out['window_s']:g}s  agg={shown}")
    print(f"  value: {value:.6g}" if value is not None
          else "  value: (no samples in window)")
    for node, v in sorted(out.get("by_node", {}).items()):
        print(f"    node {node[:12]}: {v:.6g}")
    for s in out.get("series", []):
        pts = [p[1] for p in s.get("points", ())]
        tag_txt = ",".join(f"{k}={v}" for k, v in sorted(s["tags"].items()))
        print(f"  [{s['reporter']}] {tag_txt or '(untagged)'} "
              f"{_spark(pts)}  n={len(pts)}")


def _spark(values, width: int = 40) -> str:
    """Render a value tail as a unicode sparkline (block elements)."""
    if not values:
        return ""
    tail = values[-width:]
    lo, hi = min(tail), max(tail)
    bars = "▁▂▃▄▅▆▇█"
    if hi - lo < 1e-12:
        return bars[0] * len(tail)
    return "".join(bars[int((v - lo) / (hi - lo) * (len(bars) - 1))]
                   for v in tail)


def cmd_microbenchmark(args):
    from ray_tpu.util import microbenchmark

    microbenchmark.main(scale=args.scale, as_json=args.json)


def cmd_lint(args):
    from ray_tpu.analysis import graftlint

    lint_args = []
    if args.json:
        lint_args.append("--json")
    if args.root:
        lint_args.extend(["--root", args.root])
    if args.baseline:
        lint_args.extend(["--baseline", args.baseline])
    for rule in args.rule or ():
        lint_args.extend(["--rule", rule])
    if args.list_rules:
        lint_args.append("--list-rules")
    sys.exit(graftlint.main(lint_args))


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--object-store-memory", type=int, default=2 << 30)
    p.add_argument("--no-dashboard", action="store_true")
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("job")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    for name in ("submit", "status", "logs", "stop", "list"):
        jp = jsub.add_parser(name)
        jp.add_argument("--address", default=None)
        jp.add_argument("--dashboard", default=None,
                        help="dashboard URL (overrides --address lookup)")
        if name == "submit":
            jp.add_argument("--wait", action="store_true")
            jp.add_argument("entrypoint", nargs=argparse.REMAINDER)
        elif name != "list":
            jp.add_argument("job_id")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("timeline")
    p.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    p.add_argument("--cluster", action="store_true",
                   help="merge span rings from every process in the cluster "
                        "(requires --address)")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("request",
                       help="stitched per-request serving trace: every span "
                            "recorded for one request id across router, "
                            "prefill, decode, and migration target")
    p.add_argument("request_id")
    p.add_argument("--address", default=None)
    p.add_argument("--cluster", action="store_true",
                   help="pull span rings from every process in the cluster "
                        "(requires --address)")
    p.add_argument("--chrome", default=None, metavar="OUTPUT",
                   help="also write the trace as chrome://tracing JSON")
    p.set_defaults(fn=cmd_request)

    p = sub.add_parser("events",
                       help="typed cluster events (node death, slice loss, "
                            "OOM kills, collective aborts, scale decisions, "
                            "gang restarts)")
    p.add_argument("--address", required=True)
    p.add_argument("--type", default=None,
                   help="filter by event type (e.g. SLICE_LOST)")
    p.add_argument("--severity", default=None,
                   help="filter by severity (INFO/WARNING/ERROR)")
    p.add_argument("--source", default=None,
                   help="filter by source component (gcs/raylet/...)")
    p.add_argument("--limit", type=int, default=100)
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("status")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list")
    p.add_argument("what", choices=["nodes", "actors", "pgs", "jobs",
                                    "tasks", "objects"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("memory")
    p.add_argument("--address", required=True)
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--cluster", action="store_true",
                   help="fan out to every worker's object table and "
                        "aggregate by owner/size/spill state")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("stack",
                       help="annotated thread stacks: what every process "
                            "is blocked on (hang diagnosis)")
    p.add_argument("--address", default=None)
    p.add_argument("--cluster", action="store_true",
                   help="dump every process in the cluster "
                        "(requires --address)")
    p.add_argument("--json", action="store_true",
                   help="raw structured dump instead of rendered text")
    p.add_argument("--wait-graph", action="store_true",
                   help="also print the GCS wait-graph + detector verdict")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("drain",
                       help="retire a node: immediately, or gracefully "
                            "with an advance-notice deadline")
    p.add_argument("node_id", help="hex node id (see `list nodes`)")
    p.add_argument("--address", required=True)
    p.add_argument("--deadline", type=float, default=0.0,
                   help="drain notice window in seconds: the node keeps "
                        "running this long while work and objects migrate "
                        "off it, then dies as preempted (0 = immediate)")
    p.add_argument("--reason", default="drained via scripts",
                   help="human-readable drain cause (lands in events and "
                        "death reasons)")
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser("stop")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("metrics",
                       help="windowed metric-history queries: counter "
                            "rates, gauge means, histogram quantiles "
                            "reconstructed over a trailing window from "
                            "the GCS time-series rings")
    p.add_argument("series",
                   help="metric name (see runtime/metric_defs.py, e.g. "
                        "ray_tpu_tasks_finished_total)")
    p.add_argument("--address", required=True)
    p.add_argument("--window", type=float, default=60.0,
                   help="trailing window in seconds (default 60)")
    p.add_argument("--agg", default=None,
                   help="aggregate: rate/delta (counters), mean/last "
                        "(gauges), p50..p99/mean/rate (histograms); "
                        "default picks by metric kind")
    p.add_argument("--rate", action="store_true",
                   help="shorthand for --agg rate")
    p.add_argument("--tag", action="append", default=None, metavar="K=V",
                   help="tag subset filter (repeatable)")
    p.add_argument("--json", action="store_true",
                   help="full structured reply incl. per-series points")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("microbenchmark",
                       help="core runtime ops/s (ray_perf.py analog)")
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_microbenchmark)

    p = sub.add_parser("lint",
                       help="graftlint: project-invariant static analysis "
                            "(zero-pickle hot paths, actor-init blocking, "
                            "wire schema, registries); exits nonzero on "
                            "violations")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.add_argument("--root", default=None,
                   help="repository root to lint (default: the tree the "
                        "installed ray_tpu package lives in)")
    p.add_argument("--baseline", default=None,
                   help="override the shipped baseline file")
    p.add_argument("--rule", action="append", default=None,
                   help="run only this rule (repeatable)")
    p.add_argument("--list-rules", action="store_true")
    p.set_defaults(fn=cmd_lint)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
