from ray_tpu.autoscaler.autoscaler import (  # noqa: F401
    Autoscaler,
    FakeMultiNodeProvider,
    Instance,
    InstanceType,
    NodeProvider,
)
from ray_tpu.autoscaler.instance_storage import InstanceStorage  # noqa: F401
from ray_tpu.autoscaler.monitor import AutoscalerMonitor  # noqa: F401
from ray_tpu.autoscaler.providers import (  # noqa: F401
    CommandRunner,
    GCETpuProvider,
    LocalNodeProvider,
    get_provider,
)
from ray_tpu.autoscaler.sdk import request_resources  # noqa: F401
