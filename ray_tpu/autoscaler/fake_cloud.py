"""Fake cloud instance API: an EXTERNAL-process reconciliation target.

Reference analog: the kuberay operator pattern
(python/ray/autoscaler/_private/kuberay/) — the autoscaler never creates
nodes directly; it posts desired instances to an external API (k8s) that
provisions ASYNCHRONOUSLY and can fail, and reconciles against what that
API reports. This module is the k8s stand-in: a threaded HTTP server with
lazy time-based status transitions (PENDING -> RUNNING at ready_at) and a
chaos control surface (provision delay, fail-next-N launches).

Run: python -m ray_tpu.autoscaler.fake_cloud --port 0 --ready-file PATH
API:
  POST   /instances  {"type": str, "count": int, "preemptible"?: bool}
                                                      -> {"ids": [...]}
  GET    /instances                                   -> {"instances": [...]}
  DELETE /instances/<id>                              -> {}
  POST   /control    {"provision_delay_s"?, "fail_next"?,
                      "preempt"?: id, "notice_s"?: float} -> {}

Preemption (the spot/advance-notice shape): POST /control with
{"preempt": iid, "notice_s": N} stamps `preempt_at = now + N` on a
RUNNING instance — the listing immediately exposes the pending notice
(what a real cloud's metadata server would surface), and tick() flips the
instance to PREEMPTED once the deadline passes. notice_s <= 0 models a
no-notice preemption (killed on the next tick).
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.instances: Dict[str, dict] = {}
        self.provision_delay_s = 0.0
        self.fail_next = 0

    def tick(self):
        """Lazy transitions: PENDING becomes RUNNING (or FAILED) at
        ready_at; a RUNNING instance with an expired preemption notice
        becomes PREEMPTED (the cloud kills it at the deadline)."""
        now = time.time()
        for inst in self.instances.values():
            if inst["status"] == "PENDING" and now >= inst["ready_at"]:
                inst["status"] = "FAILED" if inst["doomed"] else "RUNNING"
            if (inst["status"] == "RUNNING"
                    and inst.get("preempt_at") is not None
                    and now >= inst["preempt_at"]):
                inst["status"] = "PREEMPTED"

    def preempt(self, iid: str, notice_s: float) -> bool:
        inst = self.instances.get(iid)
        if inst is None or inst["status"] in ("TERMINATED", "FAILED",
                                              "PREEMPTED"):
            return False
        inst["preempt_at"] = time.time() + max(0.0, notice_s)
        inst["preempt_notice_s"] = notice_s
        return True

    def create(self, type_name: str, count: int,
               preemptible: bool = False) -> list:
        ids = []
        slice_id = uuid.uuid4().hex[:8] if count > 1 else None
        for i in range(count):
            iid = f"fc-{uuid.uuid4().hex[:8]}"
            doomed = False
            if self.fail_next > 0:
                self.fail_next -= 1
                doomed = True
            self.instances[iid] = {
                "id": iid, "type": type_name, "status": "PENDING",
                "slice_id": slice_id, "worker_index": i,
                "ready_at": time.time() + self.provision_delay_s,
                "doomed": doomed,
                "preemptible": bool(preemptible),
                "preempt_at": None, "preempt_notice_s": None,
            }
            ids.append(iid)
        return ids


def make_server(port: int = 0) -> ThreadingHTTPServer:
    state = _State()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _reply(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        def do_GET(self):
            if self.path == "/instances":
                with state.lock:
                    state.tick()
                    insts = [dict(i) for i in state.instances.values()]
                self._reply({"instances": insts})
            else:
                self._reply({"error": "not found"}, 404)

        def do_POST(self):
            if self.path == "/instances":
                req = self._body()
                with state.lock:
                    ids = state.create(req["type"], int(req.get("count", 1)),
                                       bool(req.get("preemptible", False)))
                self._reply({"ids": ids})
            elif self.path == "/control":
                req = self._body()
                with state.lock:
                    if "provision_delay_s" in req:
                        state.provision_delay_s = float(
                            req["provision_delay_s"])
                    if "fail_next" in req:
                        state.fail_next = int(req["fail_next"])
                    if "preempt" in req:
                        ok = state.preempt(str(req["preempt"]),
                                           float(req.get("notice_s", 0.0)))
                        if not ok:
                            return self._reply(
                                {"error": "unknown or dead instance"}, 404)
                self._reply({})
            else:
                self._reply({"error": "not found"}, 404)

        def do_DELETE(self):
            if self.path.startswith("/instances/"):
                iid = self.path.rsplit("/", 1)[1]
                with state.lock:
                    inst = state.instances.get(iid)
                    if inst is not None:
                        inst["status"] = "TERMINATED"
                self._reply({})
            else:
                self._reply({"error": "not found"}, 404)

    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    srv.state = state  # type: ignore[attr-defined]
    return srv


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--ready-file", default="")
    args = ap.parse_args()
    srv = make_server(args.port)
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"127.0.0.1:{srv.server_address[1]}")
        import os

        os.replace(tmp, args.ready_file)
    srv.serve_forever()


if __name__ == "__main__":
    main()
