"""Autoscaler v2-style reconciler, TPU-slice-aware.

Reference analog: python/ray/autoscaler/v2/ (autoscaler.py:42 Autoscaler,
Reconciler, InstanceStorage, scheduler.py ResourceDemandScheduler) with the
FakeMultiNodeProvider test pattern
(autoscaler/_private/fake_multi_node/node_provider.py:236).

TPU-native rule (SURVEY §2 mapping note + §7.10): demand for TPU chips is
rounded up to whole slices — an instance type advertising a "v5e-8" slice is
launched as a unit; loose-chip bin-packing never splits a slice.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.runtime import scheduling

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class InstanceType:
    name: str
    resources: Dict[str, float]
    max_workers: int = 100
    # TPU topology: whole-slice instances (e.g. {"TPU": 8} labeled v5e-8)
    tpu_slice: Optional[str] = None


@dataclasses.dataclass
class Instance:
    instance_id: str
    instance_type: str
    status: str = "LAUNCHING"   # LAUNCHING | RUNNING | TERMINATING
    node_id: Optional[bytes] = None
    launched_at: float = 0.0


class NodeProvider:
    """Cloud abstraction (reference: autoscaler NodeProvider plugins)."""

    def launch(self, instance_type: InstanceType) -> str:
        raise NotImplementedError

    def terminate(self, instance_id: str) -> None:
        raise NotImplementedError

    def non_terminated(self) -> List[str]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches real raylet subprocesses on this machine (test provider)."""

    def __init__(self, cluster):
        self.cluster = cluster  # ray_tpu.cluster_utils.Cluster
        self.nodes: Dict[str, object] = {}

    def launch(self, instance_type: InstanceType) -> str:
        labels = {}
        if instance_type.tpu_slice:
            labels["tpu-slice"] = f"{instance_type.tpu_slice}-{uuid.uuid4().hex[:6]}"
            labels["tpu-pod-type"] = instance_type.tpu_slice
        res = dict(instance_type.resources)
        num_cpus = res.pop("CPU", 1)
        num_tpus = res.pop("TPU", 0)
        node = self.cluster.add_node(num_cpus=num_cpus, num_tpus=num_tpus,
                                     resources=res, labels=labels)
        iid = f"fake-{uuid.uuid4().hex[:8]}"
        self.nodes[iid] = node
        return iid

    def terminate(self, instance_id: str) -> None:
        node = self.nodes.pop(instance_id, None)
        if node is not None:
            self.cluster.remove_node(node, force=False)

    def non_terminated(self) -> List[str]:
        return list(self.nodes)


class Autoscaler:
    """Reconciler: observed demand + cluster state -> launch/terminate."""

    def __init__(self, provider: NodeProvider,
                 instance_types: List[InstanceType],
                 *, idle_timeout_s: float = 60.0,
                 min_workers: int = 0, max_workers: int = 8):
        self.provider = provider
        self.instance_types = {t.name: t for t in instance_types}
        self.instances: Dict[str, Instance] = {}
        self.idle_timeout_s = idle_timeout_s
        self.min_workers = min_workers
        self.max_workers = max_workers
        self._idle_since: Dict[str, float] = {}

    # -- demand ------------------------------------------------------------

    def get_demand(self) -> List[Dict[str, float]]:
        """Unmet resource demand: queued leases per raylet + pending PGs."""
        from ray_tpu.state.api import _gcs_call, node_stats

        demand: List[Dict[str, float]] = []
        for stats in node_stats():
            for _ in range(stats.get("num_pending_leases", 0)):
                demand.append({"CPU": 1.0})  # raylet doesn't expose shapes yet
        for pg in _gcs_call("list_placement_groups"):
            if pg["state"] in ("PENDING", "RESCHEDULING"):
                demand.extend(pg["bundles"])
        return demand

    # -- reconcile ---------------------------------------------------------

    def reconcile(self, demand: Optional[List[Dict[str, float]]] = None
                  ) -> Dict[str, int]:
        """One reconciliation round; returns {"launched": n, "terminated": m}."""
        from ray_tpu.state.api import list_nodes

        if demand is None:
            demand = self.get_demand()
        nodes = [n for n in list_nodes() if n["alive"]]
        free = [dict(n["available"]) for n in nodes]

        # Unplaceable demand after bin-packing onto current free capacity.
        unmet: List[Dict[str, float]] = []
        for bundle in demand:
            placed = False
            for avail in free:
                if scheduling.fits(avail, bundle):
                    scheduling.subtract(avail, bundle)
                    placed = True
                    break
            if not placed:
                unmet.append(bundle)

        launched = 0
        to_launch = self._plan_launches(unmet)
        for type_name in to_launch:
            if len(self.instances) >= self.max_workers:
                break
            iid = self.provider.launch(self.instance_types[type_name])
            self.instances[iid] = Instance(iid, type_name, "RUNNING",
                                           launched_at=time.time())
            launched += 1

        terminated = self._terminate_idle(nodes, demand)
        return {"launched": launched, "terminated": terminated,
                "unmet_demand": len(unmet)}

    def _plan_launches(self, unmet: List[Dict[str, float]]) -> List[str]:
        """Choose instance types to cover unmet bundles. TPU demand rounds up
        to whole slices; CPU demand bin-packs into the smallest type."""
        plan: List[str] = []
        tpu_chips = sum(b.get("TPU", 0) for b in unmet)
        if tpu_chips > 0:
            slice_types = [t for t in self.instance_types.values()
                           if t.resources.get("TPU", 0) > 0]
            if slice_types:
                t = max(slice_types, key=lambda t: t.resources["TPU"])
                count = math.ceil(tpu_chips / t.resources["TPU"])
                plan.extend([t.name] * count)
        cpu_bundles = [b for b in unmet if b.get("TPU", 0) == 0 and b]
        if cpu_bundles:
            cpu_types = [t for t in self.instance_types.values()
                         if t.resources.get("TPU", 0) == 0]
            if cpu_types:
                t = max(cpu_types, key=lambda t: t.resources.get("CPU", 0))
                per_node = t.resources.get("CPU", 1)
                need = sum(b.get("CPU", 1) for b in cpu_bundles)
                plan.extend([t.name] * math.ceil(need / per_node))
        return plan

    def _terminate_idle(self, nodes, demand) -> int:
        """Terminate instances whose node has been fully idle past the
        timeout (never below min_workers; head node is never touched)."""
        terminated = 0
        if demand:
            self._idle_since.clear()
            return 0
        now = time.time()
        node_by_id = {n["node_id"]: n for n in nodes}
        for iid, inst in list(self.instances.items()):
            if len(self.instances) <= self.min_workers:
                break
            node = node_by_id.get(inst.node_id.hex() if inst.node_id else "")
            fully_idle = node is not None and \
                node["available"] == node["resources"]
            if node is None:
                # Match by provider knowledge: fall back to age-based idle.
                fully_idle = True
            if fully_idle:
                since = self._idle_since.setdefault(iid, now)
                if now - since > self.idle_timeout_s:
                    self.provider.terminate(iid)
                    del self.instances[iid]
                    self._idle_since.pop(iid, None)
                    terminated += 1
            else:
                self._idle_since.pop(iid, None)
        return terminated
