"""Autoscaler v2-style reconciler, TPU-slice-aware.

Reference analog: python/ray/autoscaler/v2/ (autoscaler.py:42 Autoscaler,
Reconciler, InstanceStorage, scheduler.py ResourceDemandScheduler) with the
FakeMultiNodeProvider test pattern
(autoscaler/_private/fake_multi_node/node_provider.py:236).

TPU-native rule (SURVEY §2 mapping note + §7.10): planning is per-bundle —
every bundle must fit whole on one planned instance (bundles are per-node),
and TPU bundles launch whole slices: an instance type advertising a "v5e-8"
slice is launched as a unit, and loose-chip bin-packing never splits a slice.
A bundle larger than every instance type is logged and left unmet.
"""

from __future__ import annotations

import dataclasses
import logging
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.runtime import scheduling

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class InstanceType:
    name: str
    resources: Dict[str, float]   # PER-HOST resources
    max_workers: int = 100
    # TPU topology: whole-slice instances (e.g. {"TPU": 8} labeled v5e-8)
    tpu_slice: Optional[str] = None
    # Multi-host slices (e.g. "v5e-32" = 8 hosts x 4 chips): launched and
    # terminated ATOMICALLY — a partial slice is useless (no ICI ring).
    hosts: int = 1

    @staticmethod
    def for_pod_type(name: str, pod_type: str,
                     cpus_per_host: float = 8.0) -> "InstanceType":
        from ray_tpu.runtime import tpu_topology

        return InstanceType(
            name=name,
            resources={"CPU": cpus_per_host,
                       "TPU": float(tpu_topology.chips_per_host(pod_type))},
            tpu_slice=pod_type,
            hosts=tpu_topology.hosts_in_slice(pod_type))


@dataclasses.dataclass
class Instance:
    instance_id: str
    instance_type: str
    status: str = "LAUNCHING"   # LAUNCHING | RUNNING | TERMINATING
    node_id: Optional[bytes] = None
    launched_at: float = 0.0
    slice_id: Optional[str] = None   # multi-host slice membership (atomic)


class NodeProvider:
    """Cloud abstraction (reference: autoscaler NodeProvider plugins)."""

    def launch(self, instance_type: InstanceType) -> str:
        raise NotImplementedError

    def launch_slice(self, instance_type: InstanceType) -> List[str]:
        """Launch a multi-host slice atomically: `instance_type.hosts` hosts
        sharing a slice name, worker ids 0..hosts-1. Default: hosts==1."""
        return [self.launch(instance_type)]

    def terminate(self, instance_id: str) -> None:
        raise NotImplementedError

    def non_terminated(self) -> List[str]:
        raise NotImplementedError

    def get_node_id(self, instance_id: str) -> Optional[bytes]:
        """Raylet node id for a launched instance, once known (else None)."""
        return None

    def preemption_notices(self) -> List[dict]:
        """Pending advance-notice preemptions from the cloud's view:
        [{"instance_id": str, "deadline": unix_ts, "notice_s": float}].
        The reconciler turns each into a GCS drain + replacement launch.
        Default: the cloud gives no notice."""
        return []


class FakeMultiNodeProvider(NodeProvider):
    """Launches real raylet subprocesses on this machine (test provider)."""

    def __init__(self, cluster):
        self.cluster = cluster  # ray_tpu.cluster_utils.Cluster
        self.nodes: Dict[str, object] = {}

    def _add_host(self, instance_type: InstanceType, labels: dict) -> str:
        res = dict(instance_type.resources)
        num_cpus = res.pop("CPU", 1)
        num_tpus = res.pop("TPU", 0)
        node = self.cluster.add_node(num_cpus=num_cpus, num_tpus=num_tpus,
                                     resources=res, labels=labels)
        iid = f"fake-{uuid.uuid4().hex[:8]}"
        self.nodes[iid] = node
        return iid

    def launch(self, instance_type: InstanceType) -> str:
        labels = {}
        if instance_type.tpu_slice:
            from ray_tpu.runtime import tpu_topology

            labels = tpu_topology.slice_labels(
                uuid.uuid4().hex[:6], instance_type.tpu_slice, 0)
        return self._add_host(instance_type, labels)

    def launch_slice(self, instance_type: InstanceType) -> List[str]:
        if instance_type.hosts <= 1 or not instance_type.tpu_slice:
            return [self.launch(instance_type)]
        from ray_tpu.runtime import tpu_topology

        slice_name = uuid.uuid4().hex[:6]
        return [self._add_host(instance_type, tpu_topology.slice_labels(
                    slice_name, instance_type.tpu_slice, wid))
                for wid in range(instance_type.hosts)]

    def terminate(self, instance_id: str) -> None:
        node = self.nodes.pop(instance_id, None)
        if node is not None:
            self.cluster.remove_node(node, force=False)

    def non_terminated(self) -> List[str]:
        return list(self.nodes)

    def get_node_id(self, instance_id: str) -> Optional[bytes]:
        node = self.nodes.get(instance_id)
        return getattr(node, "node_id", None)


class Autoscaler:
    """Reconciler: observed demand + cluster state -> launch/terminate."""

    def __init__(self, provider: NodeProvider,
                 instance_types: List[InstanceType],
                 *, idle_timeout_s: float = 60.0,
                 min_workers: int = 0, max_workers: int = 8,
                 boot_grace_s: float = 300.0):
        self.provider = provider
        self.instance_types = {t.name: t for t in instance_types}
        self.instances: Dict[str, Instance] = {}
        self.idle_timeout_s = idle_timeout_s
        self.min_workers = min_workers
        self.max_workers = max_workers
        # How long a launched instance may stay unregistered before it is
        # considered failed and reaped.
        self.boot_grace_s = boot_grace_s
        self._idle_since: Dict[str, float] = {}
        self._preempt_handled: set = set()

    # -- demand ------------------------------------------------------------

    def get_demand(self, floor: Optional[List[Dict[str, float]]] = None,
                   nodes: Optional[List[dict]] = None
                   ) -> List[Dict[str, float]]:
        """Unmet resource demand: per-scheduling-class lease backlog
        (real shapes, including cluster-wide-infeasible parked classes),
        aggregated by the GCS from raylet heartbeats — one RPC, not a
        node_stats fan-out — + pending PGs. `floor`/`nodes` can be passed
        by reconcile() so one tick issues each GCS RPC once."""
        from ray_tpu.state.api import _gcs_call

        demand: List[Dict[str, float]] = []
        for node in _gcs_call("cluster_demand"):
            for entry in node["backlog"]:
                shape = dict(entry.get("shape", {})) or {"CPU": 1.0}
                demand.extend(dict(shape)
                              for _ in range(entry.get("count", 1)))
        for pg in _gcs_call("list_placement_groups"):
            if pg["state"] in ("PENDING", "RESCHEDULING"):
                demand.extend(pg["bundles"])
        # Explicit floor from request_resources(): reference semantics are
        # about cluster SIZE — a floor bundle is satisfied by any node
        # large enough regardless of utilization, so only the remainder
        # the current nodes cannot hold BY CAPACITY becomes launch demand
        # (packing against `available` would grow a busy cluster past the
        # floor forever).
        if floor is None:
            floor = self._floor_bundles()
        if floor:
            if nodes is None:
                from ray_tpu.state.api import list_nodes

                nodes = [n for n in list_nodes() if n["alive"]]
            caps = [dict(n["resources"]) for n in nodes]
            for bundle in floor:
                for cap in caps:
                    if all(cap.get(k, 0.0) >= v for k, v in bundle.items()):
                        for k, v in bundle.items():
                            cap[k] = cap.get(k, 0.0) - v
                        break
                else:
                    demand.append(dict(bundle))
        return demand

    def _floor_bundles(self) -> List[Dict[str, float]]:
        """request_resources floor, with a last-known cache: a TRANSIENT
        GCS error must not drop operator-requested capacity for a tick
        (the next _terminate_idle would reap the floor-held nodes); only
        a GCS that does not know the method (pre-upgrade) clears it."""
        from ray_tpu.state.api import _gcs_call

        try:
            floor = [dict(b) for b in _gcs_call("get_requested_resources")]
            self._floor_cache = floor
        except Exception as e:
            if "no handler" in str(e):
                self._floor_cache = []
            else:
                logger.warning(
                    "get_requested_resources failed (%r); holding "
                    "last-known floor (%d bundles)", e,
                    len(getattr(self, "_floor_cache", [])))
            floor = list(getattr(self, "_floor_cache", []))
        return floor

    # -- preemption notices ------------------------------------------------

    def handle_preemption_notice(self, instance_id: str,
                                 deadline_s: Optional[float] = None,
                                 reason: str = "spot preemption") -> bool:
        """React to an advance preemption notice for one instance.

        Two actions, both at NOTICE time (not at the kill): (1) the
        instance's node enters the GCS DRAINING state with the notice
        window as its deadline, so the scheduler stops leasing onto it,
        its raylet migrates primary object copies, and drain-aware
        consumers (Train/RLHF) checkpoint and re-form proactively;
        (2) a replacement instance of the same type launches immediately,
        so replacement capacity races the deadline instead of waiting
        for the death to create demand. Returns True if the drain was
        issued. Idempotent per instance."""
        if instance_id in self._preempt_handled:
            return False
        inst = self.instances.get(instance_id)
        if inst is None:
            return False
        self._preempt_handled.add(instance_id)
        if deadline_s is None:
            from ray_tpu.config import cfg

            deadline_s = cfg().drain_deadline_default_s
        if inst.node_id is None:
            inst.node_id = self.provider.get_node_id(instance_id)
        drained = False
        if inst.node_id is not None:
            from ray_tpu.state.api import _gcs_call

            try:
                reply = _gcs_call("drain_node", node_id=inst.node_id,
                                  reason=reason, deadline_s=deadline_s)
                drained = bool(reply.get("ok"))
            except Exception as e:
                logger.warning("drain_node for preempted instance %s "
                               "failed: %r", instance_id, e)
        inst.status = "DRAINING"
        # Replacement launch NOW: every sibling host of a multi-host slice
        # is preempted with it (the cloud reclaims whole slices) and each
        # host's notice drains its own node, but the replacement slice
        # launches ONCE per preempted slice, not once per host notice.
        launched = 0
        t = self.instance_types.get(inst.instance_type)
        if inst.slice_id is not None:
            replaced = getattr(self, "_preempt_replaced_slices", None)
            if replaced is None:
                replaced = self._preempt_replaced_slices = set()
            if inst.slice_id in replaced:
                t = None
            else:
                replaced.add(inst.slice_id)
        if (t is not None
                and len(self.instances) + t.hosts <= self.max_workers):
            iids = self.provider.launch_slice(t)
            slice_id = uuid.uuid4().hex[:8] if t.hosts > 1 else None
            for iid in iids:
                self.instances[iid] = Instance(iid, t.name, "LAUNCHING",
                                               launched_at=time.time(),
                                               slice_id=slice_id)
            launched = len(iids)
        logger.warning(
            "preemption notice for %s (%.1fs): drain %s, +%d replacement "
            "instance(s)", instance_id, deadline_s,
            "issued" if drained else "skipped (no node binding)", launched)
        from ray_tpu.runtime import events as events_mod

        try:
            events_mod.emit(
                events_mod.AUTOSCALER_SCALE,
                f"preemption notice for instance {instance_id} "
                f"({deadline_s:.1f}s): node drain "
                f"{'issued' if drained else 'skipped'}, {launched} "
                f"replacement instance(s) launched",
                severity=events_mod.WARNING, source="autoscaler",
                labels={"instance": instance_id,
                        "deadline_s": f"{deadline_s:.1f}",
                        "launched": str(launched)})
        except Exception:
            pass
        return drained

    def _poll_preemption_notices(self) -> None:
        try:
            notices = self.provider.preemption_notices()
        except Exception:
            return
        for n in notices:
            iid = n.get("instance_id")
            if not iid or iid in self._preempt_handled:
                continue
            deadline = n.get("deadline")
            # Remaining window, not the original notice: polling latency
            # between the cloud stamping the notice and this tick seeing
            # it has already consumed part of the drain budget.
            if deadline is not None:
                notice_s = max(0.0, float(deadline) - time.time())
            else:
                notice_s = n.get("notice_s")
            self.handle_preemption_notice(iid, notice_s)

    # -- reconcile ---------------------------------------------------------

    def reconcile(self, demand: Optional[List[Dict[str, float]]] = None
                  ) -> Dict[str, int]:
        """One reconciliation round; returns {"launched": n, "terminated": m}."""
        from ray_tpu.state.api import list_nodes

        self._poll_preemption_notices()
        nodes = [n for n in list_nodes() if n["alive"]]
        # One floor fetch + one node listing per tick, shared by demand
        # accounting and idle termination (two reads could also disagree
        # mid-tick, e.g. a floor cleared between them).
        floor = self._floor_bundles()
        if demand is None:
            try:
                demand = self.get_demand(floor=floor, nodes=nodes)
            except TypeError:
                # Tests/subclasses stub get_demand with a 0-arg callable.
                demand = self.get_demand()
        alive_ids = {n["node_id"] for n in nodes}
        # A DRAINING node is alive but refuses new leases and dies at its
        # deadline — counting its capacity would suppress the very
        # replacement launch the drain notice exists to trigger.
        free = [dict(n["available"]) for n in nodes if not n.get("draining")]

        # Resolve instance -> raylet-node bindings and mark registered
        # instances RUNNING. Instances still booting (launched but not yet in
        # the GCS node table) contribute their full advertised capacity so a
        # periodic reconcile loop doesn't re-launch for the same demand every
        # tick while a slice boots — but an instance that outlives the boot
        # grace without ever registering is reaped HERE, before capacity
        # accounting: its phantom capacity must not suppress a replacement
        # launch while real demand goes unserved.
        now = time.time()
        for iid, inst in list(self.instances.items()):
            if inst.node_id is None:
                inst.node_id = self.provider.get_node_id(inst.instance_id)
            registered = (inst.node_id is not None
                          and inst.node_id.hex() in alive_ids)
            if registered:
                if inst.status != "DRAINING":
                    inst.status = "RUNNING"
                continue
            if inst.status == "DRAINING":
                # Drain deadline passed and the cloud reclaimed the node:
                # drop the record (the replacement already launched at
                # notice time; keeping this would pin max_workers).
                try:
                    self.provider.terminate(iid)
                except Exception:
                    pass
                self.instances.pop(iid, None)
                self._idle_since.pop(iid, None)
                continue
            if inst.status != "LAUNCHING":
                # Previously RUNNING but transiently absent from the alive
                # table (raylet restart, heartbeat blip): leave it to the
                # idle-timeout path rather than reaping a busy node here.
                continue
            if now - inst.launched_at > self.boot_grace_s:
                logger.warning("instance %s never registered within %.0fs; "
                               "terminating", iid, self.boot_grace_s)
                # A partial multi-host slice is useless (broken ICI ring):
                # reap every sibling host with it.
                doomed = [iid] if inst.slice_id is None else [
                    j for j, other in self.instances.items()
                    if other.slice_id == inst.slice_id]
                for j in doomed:
                    self.provider.terminate(j)
                    self.instances.pop(j, None)
                    self._idle_since.pop(j, None)
            elif inst.status == "LAUNCHING":
                free.append(dict(
                    self.instance_types[inst.instance_type].resources))

        # Unplaceable demand after bin-packing onto current + booting capacity.
        unmet: List[Dict[str, float]] = []
        for bundle in demand:
            placed = False
            for avail in free:
                if scheduling.fits(avail, bundle):
                    scheduling.subtract(avail, bundle)
                    placed = True
                    break
            if not placed:
                unmet.append(bundle)

        launched = 0
        to_launch = self._plan_launches(unmet)
        for type_name in to_launch:
            t = self.instance_types[type_name]
            if len(self.instances) + t.hosts > self.max_workers:
                break
            iids = self.provider.launch_slice(t)
            slice_id = uuid.uuid4().hex[:8] if t.hosts > 1 else None
            for iid in iids:
                self.instances[iid] = Instance(iid, type_name, "LAUNCHING",
                                               launched_at=time.time(),
                                               slice_id=slice_id)
            launched += len(iids)

        terminated = self._terminate_idle(nodes, demand, floor=floor)
        if launched or terminated:
            from ray_tpu.runtime import events as events_mod

            events_mod.emit(
                events_mod.AUTOSCALER_SCALE,
                f"scale decision: +{launched} instance(s) launched, "
                f"-{terminated} terminated ({len(unmet)} unmet bundle(s))",
                source="autoscaler",
                labels={"launched": str(launched),
                        "terminated": str(terminated),
                        "unmet": str(len(unmet))})
        return {"launched": launched, "terminated": terminated,
                "unmet_demand": len(unmet)}

    def _plan_launches(self, unmet: List[Dict[str, float]]) -> List[str]:
        """Choose instance types covering unmet bundles by per-bundle fit:
        every bundle must fit whole on one planned instance (bundles are
        per-node). TPU bundles launch whole slices (the instance type IS an
        intact ICI slice); remaining capacity of planned instances is
        first-fit packed with further bundles."""
        plan: List[str] = []
        plan_free: List[Dict[str, float]] = []
        for bundle in sorted(unmet, key=lambda b: -sum(b.values())):
            placed = False
            for cap in plan_free:
                if scheduling.fits(cap, bundle):
                    scheduling.subtract(cap, bundle)
                    placed = True
                    break
            if placed:
                continue
            candidates = [t for t in self.instance_types.values()
                          if scheduling.fits(dict(t.resources), bundle)]
            if not candidates:
                logger.warning(
                    "no instance type fits bundle %s; leaving unmet", bundle)
                continue
            # Smallest adequate type; avoid burning TPU slices on CPU work.
            t = min(candidates, key=lambda t: (t.resources.get("TPU", 0),
                                               t.hosts,
                                               sum(t.resources.values())))
            plan.append(t.name)
            # A multi-host slice contributes every host's capacity.
            cap = dict(t.resources)
            scheduling.subtract(cap, bundle)
            plan_free.append(cap)
            for _ in range(t.hosts - 1):
                plan_free.append(dict(t.resources))
        return plan

    def _demand_reserve(self, demand, nodes,
                        capacity_key: str = "available") -> set:
        """Instance ids PROTECTED from idle termination: demand bundles
        packed first-fit onto registered instances' capacities. Demand
        must not freeze scale-down wholesale — a persistent
        request_resources floor would otherwise pin every node at peak
        size forever; only the nodes the demand actually needs stay.

        capacity_key: "available" for backlog demand (queued work needs
        FREE capacity — packing against totals would let a busy node
        absorb the reservation and leave the idle node the work actually
        needs unprotected); "resources" for the request_resources floor
        (size semantics: any node large enough holds a floor bundle)."""
        node_by_id = {n["node_id"]: n for n in nodes}
        remaining: Dict[str, Dict[str, float]] = {}
        instance_node_ids = set()
        for iid, inst in self.instances.items():
            node = (node_by_id.get(inst.node_id.hex())
                    if inst.node_id else None)
            if node is not None:
                instance_node_ids.add(node["node_id"])
                remaining[iid] = dict(node[capacity_key])
        # NON-instance nodes (the head, operator-managed nodes) absorb
        # bundles too — they satisfy demand in get_demand's accounting,
        # and a bundle they hold must not pin a terminable worker here.
        for n in nodes:
            if n["node_id"] not in instance_node_ids:
                remaining[f"node:{n['node_id']}"] = dict(n[capacity_key])
        reserved: set = set()
        for bundle in demand:
            # Prefer already-reserved, then non-instance nodes (reserving
            # them is free — they are never idle-terminated anyway).
            for iid in sorted(
                    remaining,
                    key=lambda i: (i not in reserved,
                                   not i.startswith("node:"))):
                cap = remaining[iid]
                if all(cap.get(k, 0.0) >= v for k, v in bundle.items()):
                    for k, v in bundle.items():
                        cap[k] = cap.get(k, 0.0) - v
                    reserved.add(iid)
                    break
        return reserved & set(self.instances)

    def _terminate_idle(self, nodes, demand,
                        floor: Optional[List[Dict[str, float]]] = None
                        ) -> int:
        """Terminate instances whose node has been fully idle past
        idle_timeout_s (never below min_workers; head node is never touched;
        nodes the current demand needs are protected via _demand_reserve).
        Never-registered instances are reaped by reconcile() after
        boot_grace_s, independent of demand."""
        terminated = 0
        protected = (self._demand_reserve(demand, nodes, "available")
                     if demand else set())
        if floor is None:
            floor = self._floor_bundles()
        if floor:
            # The SATISFIED floor never appears in demand (get_demand
            # emits only the unmet remainder), but its holders must not
            # idle out — that would flap: terminate -> floor unmet ->
            # relaunch, every idle_timeout.
            protected |= self._demand_reserve(floor, nodes, "resources")
        now = time.time()
        node_by_id = {n["node_id"]: n for n in nodes}

        def node_of(inst):
            return node_by_id.get(inst.node_id.hex()) if inst.node_id else None

        def idle_expired(iid, inst) -> bool:
            if inst.status == "DRAINING":
                # Mid-drain: the deadline (not the idle clock) retires it.
                return False
            node = node_of(inst)
            if node is None:
                return False  # still booting (boot-grace reaping handles it)
            if node["available"] != node["resources"]:
                self._idle_since.pop(iid, None)
                return False
            since = self._idle_since.setdefault(iid, now)
            return now - since > self.idle_timeout_s

        # Group by slice: multi-host slices terminate ATOMICALLY, and only
        # when EVERY host has been idle past the timeout.
        groups: Dict[Optional[str], List[str]] = {}
        for iid, inst in self.instances.items():
            groups.setdefault(inst.slice_id or iid, []).append(iid)
        for key, iids in list(groups.items()):
            if len(self.instances) - len(iids) < self.min_workers:
                continue
            if any(iid in protected for iid in iids):
                # Reset protected nodes' idle clocks: otherwise a node
                # held by a floor for an hour is terminated with ZERO
                # grace the instant protection lapses (its pre-protection
                # timestamp is already past the timeout).
                for iid in iids:
                    self._idle_since.pop(iid, None)
                continue
            if all(idle_expired(iid, self.instances[iid]) for iid in iids):
                for iid in iids:
                    self.provider.terminate(iid)
                    del self.instances[iid]
                    self._idle_since.pop(iid, None)
                    terminated += 1
        return terminated
