"""Persistent instance table + event log for the autoscaler.

Reference analog: python/ray/autoscaler/v2/instance_manager/ —
InstanceStorage (versioned instance table the Reconciler reads/writes) and
the instance event stream. Ours is sqlite (same engine the GCS store uses),
so an autoscaler that restarts re-attaches to its launched instances
instead of leaking or double-launching them.
"""

from __future__ import annotations

import json
import sqlite3
import time
from typing import Dict, List, Optional, Tuple


class InstanceStorage:
    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS instances ("
            " instance_id TEXT PRIMARY KEY,"
            " instance_type TEXT, status TEXT, node_id BLOB,"
            " launched_at REAL, slice_id TEXT, version INTEGER)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS events ("
            " seq INTEGER PRIMARY KEY AUTOINCREMENT,"
            " ts REAL, instance_id TEXT, event TEXT, detail TEXT)")
        self._db.commit()

    # -- instance table ----------------------------------------------------

    def upsert(self, inst) -> None:
        """inst: autoscaler.Instance."""
        self._db.execute(
            "INSERT INTO instances VALUES (?,?,?,?,?,?,"
            " COALESCE((SELECT version+1 FROM instances WHERE instance_id=?),"
            " 1)) ON CONFLICT(instance_id) DO UPDATE SET"
            " instance_type=excluded.instance_type, status=excluded.status,"
            " node_id=excluded.node_id, launched_at=excluded.launched_at,"
            " slice_id=excluded.slice_id, version=version+1",
            (inst.instance_id, inst.instance_type, inst.status, inst.node_id,
             inst.launched_at, inst.slice_id, inst.instance_id))
        self._db.commit()

    def delete(self, instance_id: str) -> None:
        self._db.execute("DELETE FROM instances WHERE instance_id=?",
                         (instance_id,))
        self._db.commit()

    def load(self) -> List:
        from ray_tpu.autoscaler.autoscaler import Instance

        rows = self._db.execute(
            "SELECT instance_id, instance_type, status, node_id, launched_at,"
            " slice_id FROM instances").fetchall()
        return [Instance(r[0], r[1], r[2], r[3], r[4], r[5]) for r in rows]

    # -- event log ---------------------------------------------------------

    def log_event(self, instance_id: str, event: str,
                  detail: Optional[dict] = None) -> None:
        self._db.execute(
            "INSERT INTO events (ts, instance_id, event, detail)"
            " VALUES (?,?,?,?)",
            (time.time(), instance_id, event,
             json.dumps(detail or {}, default=repr)))
        self._db.commit()

    def events(self, instance_id: Optional[str] = None,
               limit: int = 100) -> List[Tuple]:
        if instance_id is None:
            q = ("SELECT ts, instance_id, event, detail FROM events"
                 " ORDER BY seq DESC LIMIT ?")
            rows = self._db.execute(q, (limit,)).fetchall()
        else:
            q = ("SELECT ts, instance_id, event, detail FROM events"
                 " WHERE instance_id=? ORDER BY seq DESC LIMIT ?")
            rows = self._db.execute(q, (instance_id, limit)).fetchall()
        return [(r[0], r[1], r[2], json.loads(r[3])) for r in rows]

    def close(self):
        self._db.close()
