"""Programmatic autoscaler requests.

Reference analog: python/ray/autoscaler/sdk.py `request_resources` — set
an explicit demand FLOOR the autoscaler holds even when no work is
queued (pre-scaling ahead of a known burst). Each call replaces the
previous request; `request_resources()` with no arguments clears it.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None
                      ) -> int:
    """Ask the autoscaler to scale to accommodate `bundles` (and/or
    `num_cpus` 1-CPU bundles). Returns the number of requested bundles
    now in force. The request persists until replaced."""
    from ray_tpu.state.api import _gcs_call

    req: List[Dict[str, float]] = []
    if num_cpus:
        req.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    if bundles:
        req.extend(dict(b) for b in bundles)
    return _gcs_call("request_resources", bundles=req)["count"]
