"""GCE Cloud-TPU provider: queued-resource (whole-slice) provisioning.

Reference analog: python/ray/autoscaler/_private/gcp/node_provider.py +
_private/accelerators/tpu.py:23-67 (pod metadata -> worker identity). The
TPU-native difference: capacity moves in INTACT ICI SLICES — the provider
speaks the Cloud TPU v2 REST surface's queuedResources API, where one
create provisions a whole v5e/v5p pod slice and one delete drains it;
per-host node identity comes from the node's networkEndpoints order, and
pod metadata becomes the `tpu-slice-name`/`tpu-worker-id`/`tpu-pod-type`
labels the ICI-aware STRICT_PACK scheduler keys on
(runtime/tpu_topology.py).

GceTpuFake is the recorded-API test double: a threaded HTTP server
modeling the queuedResources lifecycle (ACCEPTED -> WAITING_FOR_RESOURCES
-> PROVISIONING -> ACTIVE, time-based), recording every request so tests
assert the exact API interaction (one create per slice, one delete per
drain — never per-chip calls).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import InstanceType, NodeProvider

logger = logging.getLogger(__name__)

_LIVE_STATES = ("ACCEPTED", "WAITING_FOR_RESOURCES", "PROVISIONING",
                "ACTIVE")


# --------------------------------------------------------------- provider

class GceTpuQueuedProvider(NodeProvider):
    """Slice-granular provider over the Cloud TPU queuedResources API.

    Instance ids are `<queued_resource_id>/worker-<i>`; every
    launch_slice() is ONE queuedResources.create for the whole pod slice
    and every terminate() of any worker drains the WHOLE queued resource
    (a partial slice has no ICI ring; the reconciler already groups
    slice siblings atomically)."""

    def __init__(self, project: str, zone: str, *,
                 base_url: str = "https://tpu.googleapis.com",
                 runtime_version: str = "tpu-ubuntu2204-base",
                 cluster=None, network: str = "default",
                 auth_token_fn=None):
        """auth_token_fn: () -> Bearer token for the real API (e.g. from
        google.auth or an operator-supplied refresher). Default: fetch
        from the GCE metadata server (cached until near expiry) when
        running on GCP; test fakes need no auth."""
        self.project = project
        self.zone = zone
        self.base = base_url.rstrip("/")
        self.runtime_version = runtime_version
        self.network = network
        self.cluster = cluster          # test binding: fake VM boot
        self.auth_token_fn = auth_token_fn
        self._token: Optional[str] = None
        self._token_expiry = 0.0
        self.types: Dict[str, InstanceType] = {}   # qr_id -> type
        self._nodes: Dict[str, object] = {}        # instance id -> node
        self._deleted: set = set()

    # -- auth --------------------------------------------------------------

    def _bearer_token(self) -> Optional[str]:
        if self.auth_token_fn is not None:
            return self.auth_token_fn()
        if "googleapis.com" not in self.base:
            return None  # test fake / local relay: unauthenticated
        if self._token and time.time() < self._token_expiry:
            return self._token
        # GCE/TPU-VM metadata server (reference tpu.py:23-26 pattern).
        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/"
            "service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=10) as r:
            payload = json.loads(r.read())
        self._token = payload["access_token"]
        self._token_expiry = time.time() + payload.get("expires_in",
                                                       300) - 60
        return self._token

    # -- REST plumbing -----------------------------------------------------

    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _req(self, method: str, path: str, body: Optional[dict] = None,
             query: Optional[dict] = None):
        url = f"{self.base}/v2/{path}"
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        token = self._bearer_token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=15) as r:
            payload = r.read()
        return json.loads(payload) if payload else {}

    # -- NodeProvider ------------------------------------------------------

    def launch(self, instance_type: InstanceType) -> str:
        if instance_type.hosts > 1:
            raise ValueError(
                f"{instance_type.name} is a {instance_type.hosts}-host "
                "slice; use launch_slice()")
        return self.launch_slice(instance_type)[0]

    def launch_slice(self, instance_type: InstanceType) -> List[str]:
        if not instance_type.tpu_slice:
            raise ValueError("GceTpuQueuedProvider only launches TPU "
                             f"slices; {instance_type.name} has none")
        qr_id = f"ray-tpu-{uuid.uuid4().hex[:8]}"
        self.types[qr_id] = instance_type
        body = {
            "tpu": {"nodeSpec": [{
                "parent": self._parent(),
                "nodeId": qr_id,
                "node": {
                    "acceleratorType": instance_type.tpu_slice,
                    "runtimeVersion": self.runtime_version,
                    "networkConfig": {"network": self.network},
                    "metadata": {"ray-cluster": "ray_tpu"},
                },
            }]},
        }
        self._req("POST", f"{self._parent()}/queuedResources", body,
                  query={"queued_resource_id": qr_id})
        return [f"{qr_id}/worker-{i}" for i in range(instance_type.hosts)]

    @staticmethod
    def _split(instance_id: str):
        qr_id, _, worker = instance_id.partition("/worker-")
        return qr_id, int(worker or 0)

    def terminate(self, instance_id: str) -> None:
        qr_id, _ = self._split(instance_id)
        if qr_id in self._deleted:
            self._unbind(instance_id)
            return
        self._deleted.add(qr_id)
        try:
            self._req("DELETE", f"{self._parent()}/queuedResources/{qr_id}",
                      query={"force": "true"})
        except Exception:
            self._deleted.discard(qr_id)
            raise
        t = self.types.get(qr_id)
        for i in range(t.hosts if t else 1):
            self._unbind(f"{qr_id}/worker-{i}")

    def _unbind(self, instance_id: str):
        node = self._nodes.pop(instance_id, None)
        if node is not None and self.cluster is not None:
            self.cluster.remove_node(node, force=False)

    def non_terminated(self) -> List[str]:
        reply = self._req("GET", f"{self._parent()}/queuedResources")
        out: List[str] = []
        for qr in reply.get("queuedResources", []):
            qr_id = qr["name"].rsplit("/", 1)[-1]
            if qr.get("state", {}).get("state") not in _LIVE_STATES:
                continue
            t = self.types.get(qr_id)
            if t is not None:
                hosts = t.hosts
            else:
                # Restarted autoscaler (types empty): derive the host
                # count from the slice's acceleratorType — one nodeSpec
                # covers the whole multi-host slice, so len(nodeSpec)
                # would under-report and leak capacity via relaunches.
                from ray_tpu.runtime import tpu_topology

                accel = (qr.get("tpu", {}).get("nodeSpec", [{}])[0]
                         .get("node", {}).get("acceleratorType", ""))
                try:
                    hosts = tpu_topology.hosts_in_slice(accel)
                except Exception:
                    hosts = 1
            out.extend(f"{qr_id}/worker-{i}" for i in range(max(1, hosts)))
        return out

    def get_node_id(self, instance_id: str) -> Optional[bytes]:
        qr_id, worker = self._split(instance_id)
        try:
            qr = self._req("GET",
                           f"{self._parent()}/queuedResources/{qr_id}")
        except Exception:
            return None
        if qr.get("state", {}).get("state") != "ACTIVE":
            return None
        node = self._nodes.get(instance_id)
        if node is None:
            if self.cluster is None:
                return None  # production: the VM's raylet self-registers
            info = self._req("GET", f"{self._parent()}/nodes/{qr_id}")
            node = self._bind_fake_host(instance_id, qr_id, worker, info)
        return getattr(node, "node_id", None)

    def _bind_fake_host(self, instance_id: str, qr_id: str, worker: int,
                        info: dict):
        """Test binding: simulate the slice host's raylet boot, deriving
        the ICI labels from the API's node object exactly as the on-VM
        bootstrap derives them from instance metadata
        (tpu_topology.slice_labels; reference tpu.py:96-116)."""
        from ray_tpu.runtime import tpu_topology

        t = self.types.get(qr_id)
        pod_type = info.get("acceleratorType",
                            t.tpu_slice if t else "v5e-4")
        res = dict(t.resources) if t else {
            "CPU": 1.0, "TPU": float(tpu_topology.chips_per_host(pod_type))}
        labels = tpu_topology.slice_labels(qr_id, pod_type, worker)
        node = self.cluster.add_node(
            num_cpus=res.pop("CPU", 1), num_tpus=res.pop("TPU", 0),
            resources=res or None, labels=labels)
        self._nodes[instance_id] = node
        return node


# ---------------------------------------------------------- preemption

class GcePreemptionWatcher:
    """On-VM watcher for GCE/TPU-VM advance preemption notice.

    GCE surfaces spot/preemptible reclamation through the instance
    metadata server: `computeMetadata/v1/instance/preempted` flips to
    "TRUE" ~30 s before the kill (the ACPI G2 shutdown window). This
    thread polls that endpoint (using the metadata server's
    wait-for-change long-poll when available) and fires `callback(
    notice_s)` ONCE at the flip — the node bootstrap wires the callback
    to the autoscaler's `handle_preemption_notice` / a direct GCS
    `drain_node`, turning the cloud's notice into a cluster drain.

    `metadata_base` is overridable so tests point it at a local fake
    instead of http://metadata.google.internal."""

    def __init__(self, callback, *, poll_interval_s: float = 1.0,
                 notice_s: float = 30.0,
                 metadata_base: str = "http://metadata.google.internal"):
        self.callback = callback
        self.poll_interval_s = poll_interval_s
        self.notice_s = notice_s
        self.base = metadata_base.rstrip("/")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False

    def _preempted(self) -> bool:
        req = urllib.request.Request(
            f"{self.base}/computeMetadata/v1/instance/preempted",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.read().strip().upper() == b"TRUE"

    def _run(self):
        while not self._stop.is_set():
            try:
                if self._preempted():
                    self.fired = True
                    try:
                        self.callback(self.notice_s)
                    except Exception:
                        logger.exception("preemption callback failed")
                    return  # one-shot: the VM is going away
            except Exception:
                pass  # metadata server hiccup: keep watching
            self._stop.wait(self.poll_interval_s)

    def start(self) -> "GcePreemptionWatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="gce-preemption-watcher", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


# --------------------------------------------------------------- fake API

class _FakeState:
    def __init__(self):
        self.lock = threading.Lock()
        self.qrs: Dict[str, dict] = {}
        self.requests: List[dict] = []   # the RECORDED api interaction
        self.provision_delay_s = 0.0
        self.deny_capacity = 0           # next N creates stay WAITING

    def tick(self):
        now = time.time()
        for qr in self.qrs.values():
            st = qr["state"]["state"]
            if st in ("ACCEPTED", "WAITING_FOR_RESOURCES") and not qr.get(
                    "starved") and now >= qr["_t0"] + self.provision_delay_s:
                qr["state"]["state"] = "PROVISIONING"
            if (qr["state"]["state"] == "PROVISIONING"
                    and now >= qr["_t0"] + self.provision_delay_s):
                qr["state"]["state"] = "ACTIVE"
            if qr["state"]["state"] == "DELETING":
                qr["state"]["state"] = "SUSPENDED"


class _FakeHandler(BaseHTTPRequestHandler):
    state: _FakeState = None  # injected

    def log_message(self, *a):  # quiet
        pass

    def _send(self, code: int, body: dict):
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _record(self, body=None):
        self.state.requests.append({
            "method": self.command, "path": self.path, "body": body})

    def _parts(self):
        path, _, query = self.path.partition("?")
        return path.strip("/").split("/"), urllib.parse.parse_qs(query)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n)) if n else {}
        self._record(body)
        parts, query = self._parts()
        # /v2/projects/P/locations/Z/queuedResources?queued_resource_id=X
        if parts[-1] == "queuedResources":
            qr_id = query.get("queued_resource_id", [f"qr-{len(self.state.qrs)}"])[0]
            with self.state.lock:
                spec = body.get("tpu", {}).get("nodeSpec", [{}])[0]
                starved = False
                if self.state.deny_capacity > 0:
                    self.state.deny_capacity -= 1
                    starved = True
                self.state.qrs[qr_id] = {
                    "name": "/".join(parts[1:] + [qr_id]),
                    "tpu": body.get("tpu", {}),
                    "state": {"state": "ACCEPTED"},
                    "_t0": time.time(),
                    "starved": starved,
                    "_node": {
                        "name": "/".join(parts[1:-1]
                                         + ["nodes", qr_id]),
                        "acceleratorType": spec.get("node", {}).get(
                            "acceleratorType", "v5e-4"),
                        "runtimeVersion": spec.get("node", {}).get(
                            "runtimeVersion", ""),
                        "metadata": spec.get("node", {}).get("metadata", {}),
                    },
                }
            return self._send(200, {"name": f"operations/{qr_id}"})
        return self._send(404, {"error": "unknown POST"})

    def do_GET(self):
        self._record()
        parts, _ = self._parts()
        with self.state.lock:
            self.state.tick()
            if parts[-1] == "queuedResources":
                return self._send(200, {"queuedResources": [
                    {k: v for k, v in qr.items() if not k.startswith("_")}
                    for qr in self.state.qrs.values()]})
            if len(parts) >= 2 and parts[-2] == "queuedResources":
                qr = self.state.qrs.get(parts[-1])
                if qr is None:
                    return self._send(404, {"error": "not found"})
                return self._send(200, {k: v for k, v in qr.items()
                                        if not k.startswith("_")})
            if len(parts) >= 2 and parts[-2] == "nodes":
                qr = self.state.qrs.get(parts[-1])
                if qr is None or qr["state"]["state"] != "ACTIVE":
                    return self._send(404, {"error": "node not ready"})
                node = dict(qr["_node"])
                accel = node["acceleratorType"]
                from ray_tpu.runtime import tpu_topology

                hosts = tpu_topology.hosts_in_slice(accel)
                node["networkEndpoints"] = [
                    {"ipAddress": f"10.0.0.{i + 1}",
                     "accessConfig": {"externalIp": ""}}
                    for i in range(hosts)]
                node["state"] = "READY"
                return self._send(200, node)
        return self._send(404, {"error": "unknown GET"})

    def do_DELETE(self):
        self._record()
        parts, _ = self._parts()
        with self.state.lock:
            qr = self.state.qrs.get(parts[-1])
            if qr is None:
                return self._send(404, {"error": "not found"})
            qr["state"]["state"] = "DELETING"
        return self._send(200, {"name": f"operations/del-{parts[-1]}"})


def start_gce_fake(port: int = 0):
    """Start the recorded-API fake; returns (server, base_url, state)."""
    state = _FakeState()
    handler = type("Handler", (_FakeHandler,), {"state": state})
    server = ThreadingHTTPServer(("127.0.0.1", port), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="gce-fake-http")
    thread.start()
    host, bound = server.server_address
    return server, f"http://{host}:{bound}", state
