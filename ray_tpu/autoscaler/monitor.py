"""Autoscaler monitor: the reconcile loop daemon.

Reference analog: python/ray/autoscaler/_private/monitor.py (the process on
the head node that drives StandardAutoscaler.update() on an interval) / the
v2 autoscaler loop. Runs as a thread next to the driver or inside a
dedicated actor; persists instance state through InstanceStorage so a
restarted monitor re-attaches instead of double-launching.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)


class AutoscalerMonitor:
    def __init__(self, autoscaler, *, interval_s: float = 5.0,
                 storage=None):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self.storage = storage
        if storage is not None:
            # Re-attach: adopt instances a previous monitor launched.
            for inst in storage.load():
                self.autoscaler.instances.setdefault(inst.instance_id, inst)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rounds = 0
        self.last_result: dict = {}

    def _persist(self):
        if self.storage is None:
            return
        stored = {i.instance_id for i in self.storage.load()}
        live = set(self.autoscaler.instances)
        for iid in stored - live:
            self.storage.log_event(iid, "terminated")
            self.storage.delete(iid)
        for iid in live:
            self.storage.upsert(self.autoscaler.instances[iid])

    def step(self) -> dict:
        """One reconcile + persist round (also the unit tests' entrypoint)."""
        result = self.autoscaler.reconcile()
        self._persist()
        self.rounds += 1
        self.last_result = result
        return result

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:
                logger.exception("autoscaler reconcile round failed")

    def start(self) -> "AutoscalerMonitor":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscaler-monitor")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
