"""Node providers + command runner: the cloud-facing autoscaler edge.

Reference analog: python/ray/autoscaler/_private/ NodeProvider plugins
(aws/gcp/azure/local) and command_runner.py (SSH/docker command runners).
TPU-native providers:

  * LocalNodeProvider — spawns raylet processes on this host via the
    in-process Cluster bootstrap (the `ray start` path for one machine).
  * GCETpuProvider — constructs and (when allowed) executes `gcloud compute
    tpus tpu-vm ...` commands through a CommandRunner; slice-granular:
    create/delete act on whole TPU pod slices (queued resources), never
    individual hosts. Network egress is gated: with dry_run=True (default
    in this environment) the provider records the exact commands instead of
    executing them, which is what the tests assert on.
"""

from __future__ import annotations

import logging
import shlex
import subprocess
import uuid
from typing import Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import InstanceType, NodeProvider

logger = logging.getLogger(__name__)


class CommandRunner:
    """Runs provider shell commands (the SSHCommandRunner analog; local
    subprocess here — deployments wrap ssh/gcloud the same way)."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.history: List[str] = []

    def run(self, cmd: List[str], timeout: float = 300.0) -> str:
        line = " ".join(shlex.quote(c) for c in cmd)
        self.history.append(line)
        if self.dry_run:
            logger.info("[dry-run] %s", line)
            return ""
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode != 0:
            raise RuntimeError(
                f"command failed ({out.returncode}): {line}\n{out.stderr}")
        return out.stdout


class LocalNodeProvider(NodeProvider):
    """All "instances" are raylet processes on this machine — the
    local/on-prem provider (reference: autoscaler/_private/local)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.nodes: Dict[str, object] = {}

    def launch(self, instance_type: InstanceType) -> str:
        res = dict(instance_type.resources)
        node = self.cluster.add_node(num_cpus=res.pop("CPU", 1),
                                     num_tpus=res.pop("TPU", 0),
                                     resources=res or None)
        iid = f"local-{uuid.uuid4().hex[:8]}"
        self.nodes[iid] = node
        return iid

    def terminate(self, instance_id: str) -> None:
        node = self.nodes.pop(instance_id, None)
        if node is not None:
            self.cluster.remove_node(node, force=False)

    def non_terminated(self) -> List[str]:
        return list(self.nodes)

    def get_node_id(self, instance_id: str) -> Optional[bytes]:
        return getattr(self.nodes.get(instance_id), "node_id", None)


class GCETpuProvider(NodeProvider):
    """TPU-VM provider: slice-granular create/delete via gcloud.

    Instance ids are TPU-VM resource names; a multi-host InstanceType maps
    to ONE queued-resource create (the whole slice), matching the
    TPU rule that capacity moves in intact ICI slices. Per-host worker
    identity comes from TPU metadata at boot (runtime/tpu_topology.py reads
    TPU_WORKER_ID), not from the provider."""

    def __init__(self, project: str, zone: str, *,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 startup_script: str = "", runner: Optional[CommandRunner] = None):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self.startup_script = startup_script
        self.runner = runner or CommandRunner(dry_run=True)
        self._live: Dict[str, InstanceType] = {}

    def _name(self) -> str:
        return f"ray-tpu-{uuid.uuid4().hex[:8]}"

    def launch(self, instance_type: InstanceType) -> str:
        name = self._name()
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", name,
               "--project", self.project, "--zone", self.zone,
               "--accelerator-type", instance_type.tpu_slice or "v5e-1",
               "--version", self.runtime_version]
        if self.startup_script:
            cmd += ["--metadata",
                    f"startup-script={self.startup_script}"]
        self.runner.run(cmd, timeout=1800)
        self._live[name] = instance_type
        return name

    def launch_slice(self, instance_type: InstanceType) -> List[str]:
        # One gcloud create provisions the WHOLE slice; we return one
        # logical instance id per host so the reconciler tracks per-host
        # registration, all sharing the slice resource name.
        name = self.launch(instance_type)
        if instance_type.hosts <= 1:
            return [name]
        return [f"{name}/worker-{i}" for i in range(instance_type.hosts)]

    def terminate(self, instance_id: str) -> None:
        name = instance_id.split("/", 1)[0]
        if name not in self._live:
            return
        del self._live[name]
        self.runner.run(["gcloud", "compute", "tpus", "tpu-vm", "delete",
                         name, "--project", self.project, "--zone",
                         self.zone, "--quiet"], timeout=1800)

    def non_terminated(self) -> List[str]:
        out = []
        for name, t in self._live.items():
            if t.hosts <= 1:
                out.append(name)
            else:
                out.extend(f"{name}/worker-{i}" for i in range(t.hosts))
        return out


PROVIDERS = {
    "local": LocalNodeProvider,
    "gce_tpu": GCETpuProvider,
}


def get_provider(name: str, **kwargs) -> NodeProvider:
    if name == "fake":
        from ray_tpu.autoscaler.autoscaler import FakeMultiNodeProvider

        return FakeMultiNodeProvider(**kwargs)
    return PROVIDERS[name](**kwargs)
