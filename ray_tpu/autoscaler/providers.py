"""Node providers + command runner: the cloud-facing autoscaler edge.

Reference analog: python/ray/autoscaler/_private/ NodeProvider plugins
(aws/gcp/azure/local) and command_runner.py (SSH/docker command runners).
TPU-native providers:

  * LocalNodeProvider — spawns raylet processes on this host via the
    in-process Cluster bootstrap (the `ray start` path for one machine).
  * GCETpuProvider — constructs and (when allowed) executes `gcloud compute
    tpus tpu-vm ...` commands through a CommandRunner; slice-granular:
    create/delete act on whole TPU pod slices (queued resources), never
    individual hosts. Network egress is gated: with dry_run=True (default
    in this environment) the provider records the exact commands instead of
    executing them, which is what the tests assert on.
"""

from __future__ import annotations

import logging
import shlex
import subprocess
import uuid
from typing import Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import InstanceType, NodeProvider

logger = logging.getLogger(__name__)


class CommandRunner:
    """Runs provider shell commands (the SSHCommandRunner analog; local
    subprocess here — deployments wrap ssh/gcloud the same way)."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.history: List[str] = []

    def run(self, cmd: List[str], timeout: float = 300.0) -> str:
        line = " ".join(shlex.quote(c) for c in cmd)
        self.history.append(line)
        if self.dry_run:
            logger.info("[dry-run] %s", line)
            return ""
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode != 0:
            raise RuntimeError(
                f"command failed ({out.returncode}): {line}\n{out.stderr}")
        return out.stdout


class LocalNodeProvider(NodeProvider):
    """All "instances" are raylet processes on this machine — the
    local/on-prem provider (reference: autoscaler/_private/local)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.nodes: Dict[str, object] = {}

    def launch(self, instance_type: InstanceType) -> str:
        res = dict(instance_type.resources)
        node = self.cluster.add_node(num_cpus=res.pop("CPU", 1),
                                     num_tpus=res.pop("TPU", 0),
                                     resources=res or None)
        iid = f"local-{uuid.uuid4().hex[:8]}"
        self.nodes[iid] = node
        return iid

    def terminate(self, instance_id: str) -> None:
        node = self.nodes.pop(instance_id, None)
        if node is not None:
            self.cluster.remove_node(node, force=False)

    def non_terminated(self) -> List[str]:
        return list(self.nodes)

    def get_node_id(self, instance_id: str) -> Optional[bytes]:
        return getattr(self.nodes.get(instance_id), "node_id", None)


class GCETpuProvider(NodeProvider):
    """TPU-VM provider: slice-granular create/delete via gcloud.

    Instance ids are TPU-VM resource names; a multi-host InstanceType maps
    to ONE queued-resource create (the whole slice), matching the
    TPU rule that capacity moves in intact ICI slices. Per-host worker
    identity comes from TPU metadata at boot (runtime/tpu_topology.py reads
    TPU_WORKER_ID), not from the provider."""

    def __init__(self, project: str, zone: str, *,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 startup_script: str = "", runner: Optional[CommandRunner] = None):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self.startup_script = startup_script
        self.runner = runner or CommandRunner(dry_run=True)
        self._live: Dict[str, InstanceType] = {}

    def _name(self) -> str:
        return f"ray-tpu-{uuid.uuid4().hex[:8]}"

    def launch(self, instance_type: InstanceType) -> str:
        name = self._name()
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", name,
               "--project", self.project, "--zone", self.zone,
               "--accelerator-type", instance_type.tpu_slice or "v5e-1",
               "--version", self.runtime_version]
        if self.startup_script:
            cmd += ["--metadata",
                    f"startup-script={self.startup_script}"]
        self.runner.run(cmd, timeout=1800)
        self._live[name] = instance_type
        return name

    def launch_slice(self, instance_type: InstanceType) -> List[str]:
        # One gcloud create provisions the WHOLE slice; we return one
        # logical instance id per host so the reconciler tracks per-host
        # registration, all sharing the slice resource name.
        name = self.launch(instance_type)
        if instance_type.hosts <= 1:
            return [name]
        return [f"{name}/worker-{i}" for i in range(instance_type.hosts)]

    def terminate(self, instance_id: str) -> None:
        name = instance_id.split("/", 1)[0]
        if name not in self._live:
            return
        del self._live[name]
        self.runner.run(["gcloud", "compute", "tpus", "tpu-vm", "delete",
                         name, "--project", self.project, "--zone",
                         self.zone, "--quiet"], timeout=1800)

    def non_terminated(self) -> List[str]:
        out = []
        for name, t in self._live.items():
            if t.hosts <= 1:
                out.append(name)
            else:
                out.extend(f"{name}/worker-{i}" for i in range(t.hosts))
        return out


class CloudAPIProvider(NodeProvider):
    """Reconciling provider against an EXTERNAL cloud instance API
    (ray_tpu/autoscaler/fake_cloud.py in tests; the kuberay-operator
    pattern, reference autoscaler/_private/kuberay/): launches are POSTs
    that provision asynchronously, listings come from the API's view, and
    failures surface as instances that never reach RUNNING.

    Node materialization: a real cloud VM boots a raylet that registers
    with the GCS. When bound to an in-process Cluster (tests), the provider
    simulates that boot by adding a cluster node the first time it sees the
    instance RUNNING; get_node_id stays None while the instance PENDs,
    which is exactly what the reconciler's boot-grace logic keys on."""

    def __init__(self, api_address: str, cluster=None):
        self.api = api_address.rstrip("/")
        if not self.api.startswith(("http://", "https://")):
            self.api = f"http://{self.api}"
        self.cluster = cluster
        self.types: Dict[str, InstanceType] = {}
        self._nodes: Dict[str, object] = {}   # iid -> ClusterNode
        self._listing: Dict[str, dict] = {}
        self._listing_at = 0.0

    # -- HTTP plumbing -----------------------------------------------------
    def _req(self, method: str, path: str, body: Optional[dict] = None):
        import json as json_mod
        import urllib.request

        data = json_mod.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.api + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            return json_mod.loads(r.read())

    def _list(self) -> Dict[str, dict]:
        """Instance listing with a short cache: one reconcile tick calls
        get_node_id per booting instance, and each would otherwise be a
        full-list round-trip against a rate-limited cloud API."""
        import time as time_mod

        now = time_mod.monotonic()
        if now - self._listing_at > 0.2:
            self._listing = {
                i["id"]: i
                for i in self._req("GET", "/instances")["instances"]}
            self._listing_at = now
        return self._listing

    # -- NodeProvider ------------------------------------------------------
    def launch(self, instance_type: InstanceType) -> str:
        if instance_type.hosts > 1:
            # launch() returns ONE tracked instance; silently creating
            # hosts-1 untracked cloud instances would leak quota forever.
            raise ValueError(
                f"{instance_type.name} is a {instance_type.hosts}-host "
                "slice; use launch_slice()")
        return self.launch_slice(instance_type)[0]

    def launch_slice(self, instance_type: InstanceType) -> List[str]:
        self.types[instance_type.name] = instance_type
        ids = self._req("POST", "/instances",
                        {"type": instance_type.name,
                         "count": instance_type.hosts})["ids"]
        self._listing_at = 0.0  # mutation: next read must refetch
        return ids

    def terminate(self, instance_id: str) -> None:
        self._req("DELETE", f"/instances/{instance_id}")
        self._listing_at = 0.0
        node = self._nodes.pop(instance_id, None)
        if node is not None and self.cluster is not None:
            self.cluster.remove_node(node, force=False)

    def non_terminated(self) -> List[str]:
        listing = self._list()
        # Materialize cloud-side preemption kills: a PREEMPTED instance's
        # simulated VM dies hard (force: the raylet gets no goodbye — the
        # graceful part already happened during the drain window).
        for iid, inst in listing.items():
            if inst["status"] == "PREEMPTED" and iid in self._nodes:
                node = self._nodes.pop(iid)
                if self.cluster is not None:
                    try:
                        self.cluster.remove_node(node, force=True)
                    except Exception:
                        pass
        return [iid for iid, inst in listing.items()
                if inst["status"] in ("PENDING", "RUNNING")]

    def preemption_notices(self) -> List[dict]:
        """Advance notices from the cloud listing: RUNNING instances with a
        pending `preempt_at` (the fake cloud's /control preemption
        injection; a real API would surface the same via its feed)."""
        out = []
        for iid, inst in self._list().items():
            if inst["status"] == "RUNNING" and inst.get("preempt_at"):
                out.append({"instance_id": iid,
                            "deadline": float(inst["preempt_at"]),
                            "notice_s": inst.get("preempt_notice_s")})
        return out

    def get_node_id(self, instance_id: str) -> Optional[bytes]:
        inst = self._list().get(instance_id)
        if inst is None or inst["status"] != "RUNNING":
            return None
        node = self._nodes.get(instance_id)
        if node is None:
            if self.cluster is None:
                return None
            # Simulated VM boot: the instance's raylet comes up and
            # registers (in production this happens on the VM itself).
            t = self.types.get(inst["type"])
            res = dict(t.resources) if t else {"CPU": 1.0}
            labels = None
            if t is not None and t.tpu_slice:
                # Slice-aware placement gangs hosts by these labels
                # (runtime/tpu_topology.py:73-77); a TPU node without them
                # can never host a STRICT_PACK slice bundle.
                labels = {
                    "tpu-slice-name": inst.get("slice_id") or instance_id,
                    "tpu-worker-id": str(inst.get("worker_index", 0)),
                    "tpu-pod-type": t.tpu_slice,
                }
            node = self.cluster.add_node(
                num_cpus=res.pop("CPU", 1), num_tpus=res.pop("TPU", 0),
                resources=res or None, labels=labels)
            self._nodes[instance_id] = node
        return getattr(node, "node_id", None)


def _gce_queued(**kwargs):
    from ray_tpu.autoscaler.gce import GceTpuQueuedProvider

    return GceTpuQueuedProvider(**kwargs)


def _kuberay(**kwargs):
    from ray_tpu.autoscaler.kuberay import KubeRayProvider

    return KubeRayProvider(**kwargs)


class _CliNodeProvider(NodeProvider):
    """Shared skeleton for CLI-argv cloud providers (AWS/Azure): launch
    builds the create command and registers the instance; terminate /
    listing / liveness are identical — a booted VM's raylet registers
    itself with the GCS, so get_node_id is always None here."""

    def __init__(self, runner: Optional[CommandRunner] = None):
        self.runner = runner or CommandRunner(dry_run=True)
        self._live: Dict[str, InstanceType] = {}

    def _terminate_cmd(self, instance_id: str) -> List[str]:
        raise NotImplementedError

    def terminate(self, instance_id: str) -> None:
        if instance_id not in self._live:
            return
        del self._live[instance_id]
        self.runner.run(self._terminate_cmd(instance_id), timeout=1800)

    def non_terminated(self) -> List[str]:
        return list(self._live)

    def get_node_id(self, instance_id: str) -> Optional[bytes]:
        return None


class AwsNodeProvider(_CliNodeProvider):
    """EC2 provider via aws-CLI argv (dry-run-able like GCETpuProvider).

    Reference analog: autoscaler/_private/aws/node_provider.py — the same
    contract (tagged instances are cluster membership; launch =
    run-instances with cluster/name tags, terminate by instance id),
    expressed as recorded CLI commands instead of boto3 calls so tests
    assert the exact API interaction without credentials or egress."""

    def __init__(self, region: str, cluster_name: str = "ray-tpu", *,
                 ami: str = "resolve:ssm:/aws/service/ami-amazon-linux-"
                            "latest/al2023-ami-kernel-default-x86_64",
                 subnet_id: str = "", key_name: str = "",
                 user_data: str = "",
                 runner: Optional[CommandRunner] = None):
        super().__init__(runner)
        self.region = region
        self.cluster_name = cluster_name
        self.ami = ami
        self.subnet_id = subnet_id
        self.key_name = key_name
        self.user_data = user_data

    @staticmethod
    def _ec2_type(instance_type: InstanceType) -> str:
        # Resource shape -> instance family (the reference reads it from
        # the cluster YAML; default maps CPU count to m5 sizes).
        cpus = instance_type.resources.get("CPU", 1)
        return ("m5.large" if cpus <= 2 else
                "m5.xlarge" if cpus <= 4 else
                "m5.2xlarge" if cpus <= 8 else "m5.4xlarge")

    def launch(self, instance_type: InstanceType) -> str:
        tags = (f"ResourceType=instance,Tags=["
                f"{{Key=ray-tpu-cluster,Value={self.cluster_name}}},"
                f"{{Key=ray-tpu-node-type,Value={instance_type.name}}}]")
        # --output json: the id parse below must not depend on the
        # operator's aws-CLI output config (text/table/yaml would leak
        # the booted VM as unparseable-but-created).
        cmd = ["aws", "ec2", "run-instances", "--region", self.region,
               "--output", "json",
               "--image-id", self.ami,
               "--instance-type", self._ec2_type(instance_type),
               "--count", "1", "--tag-specifications", tags]
        if self.subnet_id:
            cmd += ["--subnet-id", self.subnet_id]
        if self.key_name:
            cmd += ["--key-name", self.key_name]
        if self.user_data:
            cmd += ["--user-data", self.user_data]
        out = self.runner.run(cmd, timeout=600)
        # EC2 ids are SERVER-assigned (unlike GCE/Azure names): parse the
        # real id from the run-instances reply, else terminate() would
        # name an id AWS never issued and leak the VM. Dry-run returns no
        # output; a placeholder id keeps the recorded lifecycle coherent.
        iid = None
        if out:
            import json as json_mod

            try:
                iid = json_mod.loads(out)["Instances"][0]["InstanceId"]
            except (ValueError, KeyError, IndexError) as e:
                raise RuntimeError(
                    f"could not parse InstanceId from run-instances "
                    f"output: {e!r}") from e
        if iid is None:
            iid = f"i-dryrun-{uuid.uuid4().hex[:12]}"
        self._live[iid] = instance_type
        return iid

    def _terminate_cmd(self, instance_id: str) -> List[str]:
        return ["aws", "ec2", "terminate-instances", "--region",
                self.region, "--instance-ids", instance_id]


class AzureNodeProvider(_CliNodeProvider):
    """Azure VM provider via az-CLI argv (dry-run-able).

    Reference analog: autoscaler/_private/_azure/node_provider.py — VMs
    tagged with the cluster name in one resource group; create/delete by
    name."""

    def __init__(self, resource_group: str, location: str,
                 cluster_name: str = "ray-tpu", *,
                 image: str = "Ubuntu2204", vm_size: str = "",
                 custom_data: str = "",
                 runner: Optional[CommandRunner] = None):
        super().__init__(runner)
        self.resource_group = resource_group
        self.location = location
        self.cluster_name = cluster_name
        self.image = image
        self.vm_size = vm_size
        self.custom_data = custom_data

    @staticmethod
    def _az_size(instance_type: InstanceType) -> str:
        cpus = instance_type.resources.get("CPU", 1)
        return ("Standard_D2s_v5" if cpus <= 2 else
                "Standard_D4s_v5" if cpus <= 4 else
                "Standard_D8s_v5" if cpus <= 8 else "Standard_D16s_v5")

    def launch(self, instance_type: InstanceType) -> str:
        name = f"ray-tpu-{uuid.uuid4().hex[:8]}"
        cmd = ["az", "vm", "create", "--name", name,
               "--resource-group", self.resource_group,
               "--location", self.location,
               "--image", self.image,
               "--size", self.vm_size or self._az_size(instance_type),
               "--tags", f"ray-tpu-cluster={self.cluster_name}",
               f"ray-tpu-node-type={instance_type.name}"]
        if self.custom_data:
            cmd += ["--custom-data", self.custom_data]
        self.runner.run(cmd, timeout=1800)
        self._live[name] = instance_type
        return name

    def _terminate_cmd(self, instance_id: str) -> List[str]:
        return ["az", "vm", "delete", "--name", instance_id,
                "--resource-group", self.resource_group, "--yes"]


PROVIDERS = {
    "local": LocalNodeProvider,
    "gce_tpu": GCETpuProvider,          # gcloud-argv shaped (dry-run-able)
    "gce_tpu_api": _gce_queued,         # Cloud TPU v2 REST queuedResources
    "cloud_api": CloudAPIProvider,
    "kuberay": _kuberay,                # RayCluster-CR patching (operator)
    "aws": AwsNodeProvider,             # aws-CLI argv (dry-run-able)
    "azure": AzureNodeProvider,         # az-CLI argv (dry-run-able)
}


def get_provider(name: str, **kwargs) -> NodeProvider:
    if name == "fake":
        from ray_tpu.autoscaler.autoscaler import FakeMultiNodeProvider

        return FakeMultiNodeProvider(**kwargs)
    return PROVIDERS[name](**kwargs)
