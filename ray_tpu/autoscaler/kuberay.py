"""KubeRay-style node provider: scale by patching a RayCluster custom
resource, let the operator make pods.

Reference analog: python/ray/autoscaler/_private/kuberay/node_provider.py —
on Kubernetes the autoscaler NEVER creates machines itself; it edits the
RayCluster CR (`spec.workerGroupSpecs[*].replicas` and
`scaleStrategy.workersToDelete`) and the KubeRay operator reconciles pods
to match. This module implements that contract against any K8s-shaped
API server:

  * `KubeRayProvider` — NodeProvider whose launch/terminate are CR
    patches and whose non_terminated is a pod list by label selector.
    One worker group per InstanceType (TPU slice groups use
    `numOfHosts` for multi-host atomicity, like KubeRay's TPU support).
  * `FakeKubeApi` — an in-process API server (HTTP, thread) holding the
    RayCluster object + pods, with a minimal operator reconcile loop, so
    the provider is tested against the real wire protocol (GET/PATCH
    JSON) rather than mocks.

Pod→node identity: the operator injects the pod name into the raylet's
labels (`kuberay.io/pod`), which is how get_node_id resolves instances —
mirroring the reference, where pod name IS the instance id.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
import uuid
from typing import Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import InstanceType, NodeProvider

logger = logging.getLogger(__name__)


class KubeRayProvider(NodeProvider):
    """Scales a RayCluster CR; the operator owns pod lifecycle."""

    def __init__(self, api_server: str, namespace: str = "default",
                 cluster_name: str = "raytpu", token: Optional[str] = None,
                 cluster=None):
        self.api = api_server.rstrip("/")
        self.ns = namespace
        self.name = cluster_name
        self.token = token
        self.cluster = cluster  # local test cluster for node identity
        self._nodes: Dict[str, object] = {}
        # The operator names pods, not us — launch() returns a SLOT id and
        # _sync() binds slots to materialized pods of the same group. The
        # autoscaler keeps accounting in slot ids; the K8s side only ever
        # sees pod names. Binding is REPLICA-granular: every slot belongs
        # to a replica-group (rid, one per launch/launch_slice), each rid
        # maps to exactly one operator replica (the ray.io/replica pod
        # label), and a rid's slots only ever bind that replica's pods —
        # so terminating slice A can never name pods of live slice B.
        self._slot_group: Dict[str, str] = {}
        self._slot_pod: Dict[str, Optional[str]] = {}
        self._slot_rid: Dict[str, str] = {}
        self._rid_replica: Dict[str, str] = {}  # rid -> replica label
        self._last_pods: Dict[str, dict] = {}   # most recent _sync view

    # -- K8s API verbs ----------------------------------------------------

    def _req(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            self.api + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/merge-patch+json"
                     if method == "PATCH" else "application/json",
                     **({"Authorization": f"Bearer {self.token}"}
                        if self.token else {})})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read() or b"{}")

    @property
    def _cr_path(self) -> str:
        return (f"/apis/ray.io/v1/namespaces/{self.ns}"
                f"/rayclusters/{self.name}")

    def _get_cr(self) -> dict:
        return self._req("GET", self._cr_path)

    def _patch_cr(self, patch: dict) -> dict:
        return self._req("PATCH", self._cr_path, patch)

    def _group_for(self, t: InstanceType) -> dict:
        cr = self._get_cr()
        for g in cr["spec"].get("workerGroupSpecs", []):
            if g["groupName"] == t.name:
                return g
        # Declare the group on first use (operator tolerates additions).
        group = {
            "groupName": t.name,
            "replicas": 0,
            "maxReplicas": t.max_workers,
            "numOfHosts": t.hosts,
            "template": {"metadata": {"labels": {
                "ray.io/cluster": self.name,
                "ray.io/group": t.name,
            }}, "spec": {"resources": dict(t.resources),
                         "tpuSlice": t.tpu_slice}},
        }
        groups = cr["spec"].get("workerGroupSpecs", []) + [group]
        self._patch_cr({"spec": {"workerGroupSpecs": groups}})
        return group

    def _set_group(self, group_name: str, **fields) -> None:
        cr = self._get_cr()
        groups = cr["spec"].get("workerGroupSpecs", [])
        for g in groups:
            if g["groupName"] == group_name:
                g.update(fields)
        self._patch_cr({"spec": {"workerGroupSpecs": groups}})

    # -- NodeProvider surface --------------------------------------------

    def _new_replica_slots(self, instance_type: InstanceType,
                           hosts: int) -> List[str]:
        g = self._group_for(instance_type)
        self._set_group(instance_type.name, replicas=g["replicas"] + 1)
        rid = uuid.uuid4().hex[:8]
        slots = []
        for i in range(hosts):
            slot = f"{instance_type.name}/{rid}-host{i}"
            self._slot_group[slot] = instance_type.name
            self._slot_pod[slot] = None
            self._slot_rid[slot] = rid
            slots.append(slot)
        return slots

    def launch(self, instance_type: InstanceType) -> str:
        """Scale-up = replicas+1. Returns a slot id; the pod materializes
        asynchronously (the operator's job) and _sync() binds it."""
        return self._new_replica_slots(instance_type, 1)[0]

    def launch_slice(self, instance_type: InstanceType) -> List[str]:
        # One replica of a multi-host group IS the whole slice
        # (numOfHosts) — atomic by construction, like KubeRay TPU pods;
        # each host pod of the replica binds to one host slot.
        return self._new_replica_slots(instance_type, instance_type.hosts)

    def _pods(self) -> List[dict]:
        sel = f"ray.io/cluster={self.name}"
        out = self._req("GET", f"/api/v1/namespaces/{self.ns}/pods"
                               f"?labelSelector={sel}")
        return out.get("items", [])

    def _sync(self) -> Dict[str, dict]:
        """Bind unbound slots to unclaimed pods at REPLICA granularity:
        each replica-group (rid) claims one whole operator replica (the
        ray.io/replica pod label) and its slots bind only that replica's
        pods. Drops slots whose bound pod disappeared. Returns
        pod-name -> pod."""
        pods = {p["metadata"]["name"]: p for p in self._pods()}
        self._last_pods = pods
        for slot, pod in list(self._slot_pod.items()):
            if pod is not None and pod not in pods:
                # Pod gone without US terminating the slot (eviction,
                # node drain, operator restart): spec.replicas still
                # demands it, so the operator WILL make a replacement —
                # unbind the slot so it rebinds rather than orphaning
                # the new pod outside our accounting forever.
                self._slot_pod[slot] = None
                self._nodes.pop(slot, None)
        claimed = {p for p in self._slot_pod.values() if p}
        # replica label -> its pods, per group
        by_replica: Dict[tuple, List[str]] = {}
        for name, p in pods.items():
            lab = p["metadata"]["labels"]
            key = (lab.get("ray.io/group"), lab.get("ray.io/replica"))
            by_replica.setdefault(key, []).append(name)
        taken_replicas = set(self._rid_replica.values())
        for slot in sorted(s for s, p in self._slot_pod.items() if p is None):
            group = self._slot_group[slot]
            rid = self._slot_rid[slot]
            replica = self._rid_replica.get(rid)
            if replica is None:
                # Claim a whole fresh replica: all pods unclaimed, right
                # group, not already owned by another rid.
                for (g, r), names in sorted(by_replica.items()):
                    if g == group and r is not None \
                            and r not in taken_replicas \
                            and not any(n in claimed for n in names):
                        replica = r
                        self._rid_replica[rid] = r
                        taken_replicas.add(r)
                        break
                if replica is None:
                    continue  # still materializing
            for name in sorted(by_replica.get((group, replica), [])):
                if name not in claimed:
                    self._slot_pod[slot] = name
                    claimed.add(name)
                    break
        return pods

    def terminate(self, instance_id: str) -> None:
        """Scale-down is precise on Kubernetes: name the pod in
        scaleStrategy.workersToDelete AND drop replicas — ONCE per
        replica, not once per host slot — so the operator can't kill an
        arbitrary survivor or a sibling slice."""
        self._sync()
        group = self._slot_group.pop(instance_id, None)
        pod_name = self._slot_pod.pop(instance_id, None)
        rid = self._slot_rid.pop(instance_id, None)
        self._nodes.pop(instance_id, None)
        if group is None:
            return
        # Replicas drop only when the LAST slot of this replica-group
        # goes; every slot's bound pod still gets named for deletion.
        last_of_replica = all(r != rid for r in self._slot_rid.values())
        if last_of_replica and rid is not None:
            self._rid_replica.pop(rid, None)
        cr = self._get_cr()
        groups = cr["spec"].get("workerGroupSpecs", [])
        for g in groups:
            if g["groupName"] == group:
                if last_of_replica and g["replicas"] > 0:
                    g["replicas"] -= 1
                if pod_name is not None:
                    strat = g.setdefault("scaleStrategy", {})
                    strat.setdefault("workersToDelete", []).append(pod_name)
        self._patch_cr({"spec": {"workerGroupSpecs": groups}})

    def pod_of(self, instance_id: str) -> Optional[str]:
        """The pod currently bound to a slot (None while booting)."""
        self._sync()
        return self._slot_pod.get(instance_id)

    def non_terminated(self) -> List[str]:
        pods = self._sync()
        out = []
        for slot, pod in self._slot_pod.items():
            if pod is None:  # replica granted, pod still materializing
                out.append(slot)
            elif pods[pod].get("status", {}).get("phase") in ("Pending",
                                                              "Running"):
                out.append(slot)
        return out

    def get_node_id(self, instance_id: str) -> Optional[bytes]:
        """In tests the fake operator backs a Running pod with a real local
        raylet (cluster.add_node), labeled with the pod name.

        Reuses the pod map from the most recent _sync (a bound slot's pod
        is stable) — the autoscaler calls this once per booting instance
        per tick and must not turn every call into a pod-list GET."""
        node = self._nodes.get(instance_id)
        if node is None and self.cluster is not None:
            pods = self._last_pods if self._slot_pod.get(instance_id) \
                else self._sync()
            pod_name = self._slot_pod.get(instance_id)
            pod = pods.get(pod_name) if pod_name else None
            if pod and pod.get("status", {}).get("phase") == "Running":
                spec = pod.get("spec", {})
                lab = pod["metadata"].get("labels", {})
                res = dict(spec.get("resources") or {"CPU": 1})
                labels = {"kuberay.io/pod": pod_name}
                if spec.get("tpuSlice"):
                    # Slice identity must be PER REPLICA and carry the host
                    # index, or multi-host gang placement (STRICT_PACK ICI
                    # contiguity) can never match kuberay nodes.
                    from ray_tpu.runtime import tpu_topology

                    group = lab.get("ray.io/group", "workers")
                    replica = lab.get("ray.io/replica", "0")
                    host = int(lab.get("ray.io/host-index", 0))
                    labels.update(tpu_topology.slice_labels(
                        f"{self.name}-{group}-r{replica}",
                        spec["tpuSlice"], host))
                node = self.cluster.add_node(
                    num_cpus=res.pop("CPU", 1), num_tpus=res.pop("TPU", 0),
                    resources=res or None, labels=labels)
                self._nodes[instance_id] = node
        return getattr(node, "node_id", None)


# ------------------------------------------------------------ fake API

class FakeKubeApi:
    """Minimal K8s API server + KubeRay operator loop, in one thread.

    Speaks real HTTP+JSON (GET CR, PATCH CR with merge semantics, list
    pods with a labelSelector) so KubeRayProvider is exercised over the
    actual wire protocol. `reconcile()` plays the operator: creates pods
    up to `replicas * numOfHosts` per group, honors workersToDelete, and
    promotes Pending pods to Running after one round (configurable)."""

    def __init__(self, namespace: str = "default",
                 cluster_name: str = "raytpu", token: Optional[str] = None,
                 pending_rounds: int = 1):
        import http.server

        self.ns = namespace
        self.name = cluster_name
        self.token = token
        self.pending_rounds = pending_rounds
        self.cr = {"apiVersion": "ray.io/v1", "kind": "RayCluster",
                   "metadata": {"name": cluster_name,
                                "namespace": namespace},
                   "spec": {"workerGroupSpecs": []}}
        self.pods: Dict[str, dict] = {}
        self._lock = threading.Lock()
        api = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authed(self):
                if api.token is None:
                    return True
                return (self.headers.get("Authorization")
                        == f"Bearer {api.token}")

            def do_GET(self):
                if not self._authed():
                    return self._send(401, {"reason": "Unauthorized"})
                with api._lock:
                    if self.path.startswith("/apis/ray.io/v1/"):
                        return self._send(200, api.cr)
                    if "/pods" in self.path:
                        sel = ""
                        if "labelSelector=" in self.path:
                            sel = self.path.split("labelSelector=")[1]
                        k, _, v = sel.partition("%3D")
                        if not v:
                            k, _, v = sel.partition("=")
                        items = [p for p in api.pods.values()
                                 if not v or
                                 p["metadata"]["labels"].get(k) == v]
                        return self._send(200, {"items": items})
                return self._send(404, {})

            def do_PATCH(self):
                if not self._authed():
                    return self._send(401, {"reason": "Unauthorized"})
                n = int(self.headers.get("Content-Length", 0))
                patch = json.loads(self.rfile.read(n) or b"{}")
                with api._lock:
                    # merge-patch at the spec level (replace lists, like
                    # application/merge-patch+json)
                    for k, v in patch.get("spec", {}).items():
                        api.cr["spec"][k] = v
                    return self._send(200, api.cr)

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="kuberay-fake-http")
        self._thread.start()

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def reconcile(self) -> None:
        """One operator round: pods converge toward the CR. Pods are
        managed per REPLICA (a multi-host group's replica = numOfHosts
        pods sharing a ray.io/replica label), as the real operator does
        for TPU worker groups."""
        with self._lock:
            for g in self.cr["spec"].get("workerGroupSpecs", []):
                group = g["groupName"]
                hosts = g.get("numOfHosts", 1)
                strat = g.get("scaleStrategy", {})
                for name in strat.get("workersToDelete", []):
                    self.pods.pop(name, None)
                if strat:
                    g["scaleStrategy"] = {}
                mine = [p for p in self.pods.values()
                        if p["metadata"]["labels"].get("ray.io/group")
                        == group]
                replicas = {}
                for p in mine:
                    r = p["metadata"]["labels"].get("ray.io/replica")
                    replicas.setdefault(r, []).append(p)
                want = g["replicas"]

                def make_pod(r, host_idx):
                    tmpl = g.get("template", {})
                    name = f"{self.name}-{group}-{uuid.uuid4().hex[:6]}"
                    labels = dict(tmpl.get("metadata", {}).get("labels", {}))
                    labels["ray.io/replica"] = r
                    labels["ray.io/host-index"] = str(host_idx)
                    self.pods[name] = {
                        "metadata": {"name": name, "labels": labels},
                        "spec": dict(tmpl.get("spec", {})),
                        "status": {"phase": "Pending", "_age": 0},
                    }

                # heal partial replicas (evicted host pods) first
                for r, pods_r in replicas.items():
                    if 0 < len(pods_r) < hosts:
                        used = {p["metadata"]["labels"]
                                .get("ray.io/host-index") for p in pods_r}
                        for i in range(hosts):
                            if str(i) not in used:
                                make_pod(r, i)
                # new replicas on free indices, all hosts at once
                idx = 0
                while len(replicas) < want:
                    while str(idx) in replicas:
                        idx += 1
                    r = str(idx)
                    replicas[r] = [None]  # placeholder: now occupied
                    for i in range(hosts):
                        make_pod(r, i)
                # excess replicas reaped whole (highest index first)
                for r in sorted(replicas, reverse=True)[:max(
                        len(replicas) - want, 0)]:
                    for name in [n for n, p in self.pods.items()
                                 if p["metadata"]["labels"].get(
                                     "ray.io/group") == group
                                 and p["metadata"]["labels"].get(
                                     "ray.io/replica") == r]:
                        self.pods.pop(name, None)
            for p in self.pods.values():
                st = p["status"]
                if st["phase"] == "Pending":
                    st["_age"] += 1
                    if st["_age"] >= self.pending_rounds:
                        st["phase"] = "Running"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
