"""Central runtime config table: typed tunables, env-overridable.

Reference analog: src/ray/common/ray_config_def.h (223 RAY_CONFIG macros,
overridable via RAY_* env vars and the _system_config dict passed at init,
serialized to components). Ours: one table; override precedence is
    _system_config (init kwarg)  >  RAY_TPU_<NAME> env var  >  default.
Components read `cfg().<name>` at use time, so test fixtures and
_system_config can retune without import-order games.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Tuple

# name -> (type, default, doc)
_DEFS: Dict[str, Tuple[type, Any, str]] = {
    # -- core worker -------------------------------------------------------
    "inline_result_max": (int, 100 * 1024,
                          "max bytes for inline (non-plasma) task results"),
    "lease_idle_timeout_s": (float, 1.0,
                             "idle worker lease kept warm before return"),
    "lease_max_inflight_requests": (int, 64,
                                    "outstanding worker-lease requests per "
                                    "scheduling key"),
    "actor_max_inflight_calls": (int, 128,
                                 "pipelined in-flight calls per actor client"),
    "pull_chunk_bytes": (int, 4 << 20, "chunk size for remote object pulls"),
    "lineage_max_entries": (int, 100_000, "owner-side lineage cap"),
    "max_dependency_reconstructions": (int, 3,
                                       "per-task cap on recursive lost-arg "
                                       "recoveries before the error surfaces"),
    "reconstruction_attempts": (int, 3,
                                "re-executions before an object is lost"),
    # -- raylet / GCS ------------------------------------------------------
    "heartbeat_interval_s": (float, 2.0, "raylet resource heartbeat period"),
    "lease_batch_max": (int, 64,
                        "lease requests coalesced into one "
                        "LeaseBatchRequestMsg frame per raylet per pump "
                        "(the raylet grants the batch in one scheduling "
                        "pass)"),
    "worker_prestart": (int, 0,
                        "idle workers spawned at raylet start (0 = spawn on "
                        "first lease; capped by the node's CPU count)"),
    "job_keepalive_interval_s": (float, 2.0,
                                 "driver job-heartbeat period (owner-death "
                                 "detection for auto-started clusters)"),
    "health_check_interval_s": (float, 2.0, "GCS node health check period"),
    "health_check_failure_threshold": (int, 3,
                                       "missed health checks before a node "
                                       "is declared dead"),
    "worker_monitor_interval_s": (float, 0.2,
                                  "raylet child-process poll period"),
    "worker_pool_max_idle": (int, 8,
                             "idle workers kept per raylet; beyond this the "
                             "oldest idle worker is terminated (bounds pool "
                             "growth across distinct runtime_envs)"),
    "runtime_env_cache_bytes": (int, 10 << 30,
                                "per-node budget for materialized runtime-env "
                                "URIs (packages, pip venvs); unpinned URIs "
                                "evict LRU-first beyond this"),
    "pg_retry_interval_s": (float, 0.2,
                            "GCS retry period for PENDING placement groups"),
    "memory_monitor_interval_s": (float, 1.0, "OOM monitor sample period"),
    "memory_usage_threshold": (float, 0.95,
                               "fraction of system memory triggering the "
                               "OOM killer"),
    # -- object store ------------------------------------------------------
    "object_store_memory_default": (int, 2 << 30,
                                    "default shm store capacity bytes"),
    "spill_chunk_bytes": (int, 8 << 20, "spill file IO chunk"),
    "spill_high_watermark": (float, 0.85,
                             "store fill fraction where the raylet starts "
                             "proactive background spilling (0 disables)"),
    "spill_low_watermark": (float, 0.70,
                            "proactive spilling stops below this fill "
                            "fraction"),
    "pull_admission_concurrency": (int, 16,
                                   "concurrent cross-node chunk reads a "
                                   "raylet serves (admission control)"),
    "broadcast_fanout": (int, 2, "relay-tree fanout for object broadcast"),
    # -- data --------------------------------------------------------------
    "data_store_highwater": (float, 0.8,
                             "object-store fill fraction where dataset "
                             "producers start throttling"),
    "data_max_in_flight": (int, 8,
                           "bounded in-flight block tasks per stage"),
    "data_task_timeout_s": (float, 600.0, "per block-task wait timeout"),
    # -- serve -------------------------------------------------------------
    "serve_autoscale_interval_s": (float, 1.0, "controller autoscale tick"),
    "serve_handle_refresh_s": (float, 1.0,
                               "handle replica-set re-poll period"),
    "serve_replica_health_timeout_s": (float, 300.0,
                                       "replica construction deadline"),
    # -- llm engine --------------------------------------------------------
    "llm_pipeline_depth": (int, 4,
                           "async decode steps in flight (latency hiding)"),
    "llm_prefill_chunk": (int, 128, "default chunked-prefill token budget"),
    # -- observability -----------------------------------------------------
    "task_events_max": (int, 10_000,
                        "task state events retained by the GCS"),
    "task_events_flush_interval_s": (float, 1.0,
                                     "worker-side task event batch period"),
    "event_flush_batch_max": (int, 2000,
                              "task events per TaskEventBatchMsg frame; a "
                              "fuller buffer ships in multiple frames on "
                              "the same tick"),
    "gcs_ring_shards": (int, 16,
                        "per-node shards of the GCS task-event ring; "
                        "ingest and index upkeep are O(shard), reads "
                        "merge across shards"),
    "cluster_events_max": (int, 10_000,
                           "structured cluster events retained by the GCS "
                           "event ring (see runtime/events.py)"),
    "stall_detector_interval_s": (float, 2.0,
                                  "GCS wait-graph detector tick period "
                                  "(cycle -> DEADLOCK_DETECTED, old edge "
                                  "-> TASK_STALLED)"),
    "stall_threshold_s": (float, 30.0,
                          "a wait-graph edge blocked longer than this is "
                          "reported as TASK_STALLED"),
    "wait_edge_max_age_s": (float, 15.0,
                            "GCS drops a reporter's wait edges not "
                            "refreshed within this window (crashed or "
                            "unblocked worker)"),
    "metrics_history_enabled": (bool, True,
                                "GCS folds every metrics flush into sharded "
                                "time-series rings (windowed queries, "
                                "link utilization, alerting); off = "
                                "latest-snapshot-only, the pre-history "
                                "behavior"),
    "metrics_history_max_bytes": (int, 8 << 20,
                                  "byte budget for the GCS metric-history "
                                  "rings; oldest points are evicted first "
                                  "once the estimate crosses it"),
    "alert_eval_interval_s": (float, 2.0,
                              "GCS alert-table evaluation tick period "
                              "(rules in runtime/alert_defs.py -> "
                              "ALERT_FIRING / ALERT_RESOLVED events)"),
    # -- collectives -------------------------------------------------------
    "collective_watchdog_interval_s": (float, 1.0,
                                       "peer-liveness/abort poll period of "
                                       "the collective watchdog during "
                                       "blocking ops"),
    "collective_peer_miss_threshold": (int, 3,
                                       "consecutive stale watchdog "
                                       "heartbeats before a collective peer "
                                       "is declared lost and the group "
                                       "aborts"),
    "collective_op_timeout_s": (float, 120.0,
                                "per-op deadline for blocking out-of-graph "
                                "collective ops"),
    "collective_topology": (str, "ring",
                            "out-of-graph collective data plane: 'ring' "
                            "(chunked ring algorithms over p2p links, "
                            "zero-pickle raw frames) or 'hub' (legacy "
                            "rank-0 star, pickled payloads)"),
    "collective_chunk_bytes": (int, 1 << 20,
                               "chunk size for ring collective transfers; "
                               "large tensors pipeline across hops in "
                               "chunks of this size and per-op scratch "
                               "memory stays bounded at one chunk"),
    "ddp_bucket_bytes": (int, 4 << 20,
                         "gradient-coalescing bucket size for "
                         "allreduce_gradients; each per-dtype bucket "
                         "launches its ring allreduce as it fills so "
                         "reduction overlaps the remaining flatten work"),
    # -- rlhf --------------------------------------------------------------
    "rlhf_placement_check_interval": (int, 1,
                                      "PPO iterations between adaptive "
                                      "placement evaluations"),
    "rlhf_rollout_frac_high": (float, 0.60,
                               "rollout share of iteration wall time above "
                               "which the adaptive policy disaggregates "
                               "(generation dominates: give the generator "
                               "its own gang and KV pool)"),
    "rlhf_rollout_frac_low": (float, 0.35,
                              "rollout share below which the adaptive "
                              "policy re-colocates (updates dominate: "
                              "reclaim the slice, cheap in-place sync)"),
    "rlhf_kv_pressure_high": (float, 0.75,
                              "KV pool occupancy fraction treated as "
                              "generator memory pressure; at/above this a "
                              "colocated generator disaggregates even if "
                              "rollout time alone would not justify it"),
    "rlhf_placement_min_dwell": (int, 2,
                                 "iterations a placement mode must persist "
                                 "before the policy may switch again "
                                 "(hysteresis against signal flapping)"),
    # -- train -------------------------------------------------------------
    "train_poll_interval_s": (float, 0.2, "controller worker poll period"),
    "train_elastic_check_interval_s": (float, 10.0,
                                       "elastic scaling evaluation period"),
    "train_restart_resource_wait_s": (float, 30.0,
                                      "max wait for cluster capacity to fit "
                                      "the worker group before a failure "
                                      "restart attempt (gang restarts race "
                                      "the autoscaler replacing a slice)"),
    "train_drain_check_interval_s": (float, 1.0,
                                     "how often the Train controller polls "
                                     "for NODE_DRAINING events overlapping "
                                     "its worker group (must be well under "
                                     "the shortest expected drain notice)"),
    # -- checkpoint plane ----------------------------------------------------
    "ckpt_fsync": (bool, True,
                   "fsync shard/manifest files before the atomic rename; "
                   "disable only in tests where durability is irrelevant"),
    "ckpt_commit_wait_s": (float, 60.0,
                           "how long rank 0's persister waits for the last "
                           "rank's manifest commit before reporting the "
                           "save as uncommitted"),
    "ckpt_flush_timeout_s": (float, 30.0,
                             "max wait for in-flight background persists "
                             "when a worker group quiesces (drain/resize)"),
    "ckpt_replicate": (bool, False,
                       "replicate completed checkpoint shards to peer "
                       "object stores via the broadcast fanout tree and "
                       "register them in the GCS relocation table"),
    "ckpt_replicate_timeout_s": (float, 60.0,
                                 "per-shard timeout for the replication "
                                 "fanout"),
    # -- drain / preemption --------------------------------------------------
    "drain_deadline_default_s": (float, 30.0,
                                 "drain notice window used when an "
                                 "autoscaler preemption notice carries no "
                                 "explicit deadline"),
    "actor_restart_capacity_wait_s": (float, 30.0,
                                      "max wait for a feasible node during "
                                      "an actor restart (a preempted node's "
                                      "replacement races registration) "
                                      "before the restart fails"),
}


class RayTpuConfig:
    def __init__(self):
        self._values: Dict[str, Any] = {}
        for name, (typ, default, _doc) in _DEFS.items():
            env = os.environ.get(f"RAY_TPU_{name.upper()}")
            if env is not None:
                try:
                    self._values[name] = (typ(env) if typ is not bool
                                          else env not in ("0", "false", ""))
                except ValueError:
                    raise ValueError(
                        f"bad value for RAY_TPU_{name.upper()}: {env!r}")
            else:
                self._values[name] = default

    def __getattr__(self, name: str):
        try:
            return self.__dict__["_values"][name]
        except KeyError:
            raise AttributeError(f"unknown config {name!r}") from None

    def apply_overrides(self, overrides: Dict[str, Any]):
        """init(_system_config=...) path; unknown keys are an error (typos
        must not silently no-op)."""
        for k, v in overrides.items():
            if k not in _DEFS:
                raise ValueError(f"unknown system config key {k!r}")
            self._values[k] = _DEFS[k][0](v)

    def as_dict(self) -> Dict[str, Any]:
        return dict(self._values)


_instance = None
_lock = threading.Lock()


def cfg() -> RayTpuConfig:
    global _instance
    if _instance is None:
        with _lock:
            if _instance is None:
                _instance = RayTpuConfig()
    return _instance


def reset_for_testing():
    global _instance
    _instance = None
