"""Cluster state introspection (`ray_tpu.state.*`).

Reference analog: python/ray/util/state/__init__.py re-exporting the list_*
API surface."""

from ray_tpu.state.api import (  # noqa: F401
    cluster_alerts,
    dump_cluster_spans,
    dump_cluster_stacks,
    link_utilization,
    list_actors,
    list_cluster_events,
    list_cluster_objects,
    list_jobs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    metrics_history,
    node_stats,
    summarize_objects,
    summary,
    wait_graph,
)
