"""State API: cluster introspection.

Reference analog: python/ray/util/state/ (api.py — `ray list actors/nodes/
objects/...`). Queries go to the GCS (and per-node raylets for live stats).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core import worker as worker_mod


def _gcs_call(method: str, **kw):
    core = worker_mod.global_worker()
    return core.io.run(core.gcs.call(method, **kw))


def list_nodes() -> List[dict]:
    out = []
    for n in _gcs_call("get_nodes", only_alive=False):
        out.append({
            "node_id": n["node_id"].hex(),
            "address": f"{n['address'][0]}:{n['address'][1]}",
            "alive": n["alive"],
            "is_head": n["is_head"],
            "resources": n["resources"],
            "available": n["available"],
            "labels": n["labels"],
            "draining": bool(n.get("draining", False)),
            "drain_reason": n.get("drain_reason", ""),
            "drain_deadline": n.get("drain_deadline", 0.0),
            "death_reason": n.get("death_reason", ""),
        })
    return out


def list_actors() -> List[dict]:
    out = []
    for a in _gcs_call("list_actors"):
        out.append({
            "actor_id": a["actor_id"].hex(),
            "class_name": a["class_name"],
            "name": a["name"],
            "state": a["state"],
            "node_id": a["node_id"].hex() if a["node_id"] else None,
            "restarts": a["restarts_used"],
        })
    return out


def list_placement_groups() -> List[dict]:
    out = []
    for pg in _gcs_call("list_placement_groups"):
        out.append({
            "placement_group_id": pg["placement_group_id"].hex(),
            "name": pg["name"],
            "strategy": pg["strategy"],
            "state": pg["state"],
            "bundles": pg["bundles"],
            "locations": [loc.hex() if loc else None
                          for loc in pg["locations"]],
        })
    return out


def list_jobs() -> List[dict]:
    return _gcs_call("get_jobs")


def list_tasks(state: Optional[str] = None, name: Optional[str] = None,
               limit: int = 1000) -> List[dict]:
    """Latest state per task (SUBMITTED/FINISHED/FAILED), newest first.
    Filters: exact `state`, substring `name`. Reference analog:
    `ray list tasks` over GcsTaskManager (python/ray/util/state/)."""
    return _gcs_call("list_tasks", state=state, name=name, limit=limit)


def list_objects(limit: int = 1000) -> List[dict]:
    """Owned objects of THIS process: id, borrower/container counts,
    locations, spill state (reference: `ray list objects` scoped
    cluster-wide; this is owner-scoped — each owner knows its own
    objects' truth. For the cluster-wide view see
    `summarize_objects()`)."""
    return worker_mod.global_worker().object_table(limit=limit)


def list_cluster_objects(limit: int = 1000) -> List[dict]:
    """Every owner's object table, cluster-wide: this process's own plus,
    per alive node, each worker's (the raylet fans out the same
    `list_objects` RPC its workers answer). Unreachable nodes/workers are
    skipped — a partial table beats none."""
    from ray_tpu.runtime.rpc import RpcClient

    core = worker_mod.global_worker()
    rows = list(core.object_table(limit=limit))
    for n in _gcs_call("get_nodes"):
        async def fetch(addr=tuple(n["address"])):
            client = RpcClient(*addr)
            await client.connect(timeout=5)
            try:
                return await client.call("list_objects", limit=limit,
                                         timeout=15)
            finally:
                await client.close()

        try:
            reply = core.io.run(fetch(), timeout=20)
        except Exception:
            continue
        rows.extend(reply.get("objects", ()))
    return rows


def summarize_objects(limit: int = 1000) -> Dict:
    """Cluster-wide object summary aggregated by owner: counts, known
    bytes, spill state (`scripts memory --cluster` backend)."""
    rows = list_cluster_objects(limit=limit)
    owners: Dict[str, dict] = {}
    for row in rows:
        o = owners.setdefault(row.get("owner") or "?", {
            "objects": 0, "bytes": 0, "spilled": 0, "spilled_bytes": 0,
            "pinned": 0, "borrowed": 0, "in_memory": 0})
        o["objects"] += 1
        size = row.get("size")
        if size:
            o["bytes"] += size
            if row.get("spilled"):
                o["spilled_bytes"] += size
        if row.get("spilled"):
            o["spilled"] += 1
        if row.get("pinned"):
            o["pinned"] += 1
        if row.get("borrowers"):
            o["borrowed"] += 1
        if row.get("in_memory"):
            o["in_memory"] += 1
    return {
        "total_objects": len(rows),
        "total_bytes": sum(o["bytes"] for o in owners.values()),
        "total_spilled": sum(o["spilled"] for o in owners.values()),
        "total_spilled_bytes": sum(o["spilled_bytes"]
                                   for o in owners.values()),
        "owners": owners,
    }


def node_stats() -> List[dict]:
    """Live per-raylet stats (workers, leases, object store usage)."""
    import asyncio

    from ray_tpu.runtime.rpc import RpcClient

    core = worker_mod.global_worker()
    stats = []
    for n in _gcs_call("get_nodes"):
        async def fetch(addr=tuple(n["address"])):
            client = RpcClient(*addr)
            await client.connect(timeout=5)
            try:
                return await client.call("node_stats", timeout=10)
            finally:
                await client.close()

        try:
            s = core.io.run(fetch(), timeout=15)
            s["node_id"] = s["node_id"].hex()
            stats.append(s)
        except Exception:
            pass
    return stats


def list_cluster_events(event_type: Optional[str] = None,
                        severity: Optional[str] = None,
                        source: Optional[str] = None,
                        limit: int = 100) -> List[dict]:
    """Typed cluster events from the GCS ring (runtime/events.py), newest
    first. Filters are exact matches on the record's type/severity/source
    fields (e.g. event_type="SLICE_LOST", severity="ERROR")."""
    return _gcs_call("list_events", event_type=event_type, severity=severity,
                     source=source, limit=limit)


def dump_cluster_spans() -> List[tuple]:
    """Pull every per-process span ring in the cluster.

    Returns [(label, spans), ...]: this process's own ring plus, per alive
    node, the raylet's ring and each of its workers' (the raylet fans out
    to its local workers over the same `dump_spans` RPC). Unreachable
    nodes are skipped — a partial timeline beats none. Feed the result to
    `tracing.merge_spans` for one chrome trace."""
    import os

    from ray_tpu.runtime.rpc import RpcClient
    from ray_tpu.util import tracing

    core = worker_mod.global_worker()
    groups = [(f"driver:{os.getpid()}", tracing.get_spans())]
    for n in _gcs_call("get_nodes"):
        async def fetch(addr=tuple(n["address"])):
            client = RpcClient(*addr)
            await client.connect(timeout=5)
            try:
                return await client.call("dump_spans", timeout=15)
            finally:
                await client.close()

        try:
            reply = core.io.run(fetch(), timeout=20)
        except Exception:
            continue
        for proc in reply.get("processes", ()):
            groups.append((proc["label"], proc["spans"]))
    return groups


def request_trace(request_id: str, cluster: bool = False) -> Dict:
    """Stitched end-to-end trace for one LLM serving request.

    The trace id derives deterministically from the request id
    (`tracing.request_trace_id`), so spans recorded by ANY process that
    touched the request — router, prefill replica, decode replica,
    migration target — are matched by id alone with no context
    propagation. With ``cluster=True`` every per-process span ring in the
    cluster is pulled (`dump_cluster_spans`); otherwise only this
    process's ring is searched (the in-process serving path records
    everything locally). Spans come back sorted by start time, each
    annotated with the recording process label."""
    import os

    from ray_tpu.util import tracing

    want = tracing.request_trace_id(request_id).hex()
    if cluster:
        try:
            groups = dump_cluster_spans()
        except Exception:
            groups = [(f"driver:{os.getpid()}", tracing.get_spans())]
    else:
        groups = [(f"driver:{os.getpid()}", tracing.get_spans())]
    spans, seen = [], set()
    for label, group in groups:
        for s in group:
            args = s.get("args") or {}
            if args.get("trace_id") != want:
                continue
            sid = args.get("span_id")
            if sid and sid in seen:
                continue  # same ring reachable via two fan-out paths
            seen.add(sid)
            ev = dict(s)
            ev["process"] = label
            spans.append(ev)
    spans.sort(key=lambda s: s.get("ts", 0.0))
    return {"request_id": request_id, "trace_id": want, "spans": spans}


def wait_graph() -> Dict:
    """The GCS-assembled cluster wait-graph: who is blocked on what
    (`edges`), active deadlock cycles (`cycles`), and the detector's
    current `stalled_tasks`/`deadlocks` counts."""
    return _gcs_call("wait_graph")


def metrics_history(name: str, tags: Optional[Dict[str, str]] = None,
                    window_s: float = 60.0, agg: Optional[str] = None,
                    points_limit: int = 240) -> Dict:
    """Windowed query over the GCS metric-history rings.

    `name` is a series from runtime/metric_defs.py; `tags` is a subset
    filter on its tag sets. `agg` picks the windowed aggregate —
    counters: `rate` (default, per second) / `delta`; gauges: `mean`
    (default) / `last`; histograms: `p50`/`p90`/`p99`... (p99 default) /
    `mean` / `rate` — quantiles are reconstructed from the per-flush
    bucket deltas recorded in the window, not from lifetime cumulative
    state. Returns the aggregate `value`, the per-node contribution
    split (`by_node`), and per-reporter point tails (`series`) for
    plotting. CLI twin: `scripts metrics <series> [--window N]`."""
    return _gcs_call("metrics_history", name=name, tags=tags,
                     window_s=window_s, agg=agg, points_limit=points_limit)


def link_utilization(window_s: float = 30.0) -> Dict:
    """Observed per-link bandwidth matrix over the trailing window,
    derived from the (op, algo)-tagged collective byte counters in the
    history rings and attributed per ICI ring link (slice-labeled nodes,
    via their `tpu-worker-id` ring order) or host/DCN egress (unlabeled
    nodes). The measured-goodput feed for contention-aware placement
    (ROADMAP item 3)."""
    return _gcs_call("link_utilization", window_s=window_s)


def cluster_alerts() -> Dict:
    """Current alert-rule states (runtime/alert_defs.py evaluated on the
    GCS alert tick): every rule with its state (`ok`/`firing`), last
    observed value, and `since` timestamp, plus the `firing` name list.
    Transitions land in the event ring as ALERT_FIRING/ALERT_RESOLVED."""
    return _gcs_call("list_alerts")


def dump_cluster_stacks() -> List[dict]:
    """Annotated stack dumps from every process in the cluster.

    Returns render_stacks() dicts: this process's own, plus per alive
    node the raylet's and each of its workers' (the raylet fans out the
    same `dump_stacks` RPC). Each thread carries its frames, its live
    blocked-on record (object get with id + owner, collective op with
    group/op id, channel read), and the task/actor it is executing.
    Unreachable nodes are skipped. Render with
    `utils.debug.format_stacks`."""
    import os

    from ray_tpu.runtime.rpc import RpcClient
    from ray_tpu.utils import debug

    core = worker_mod.global_worker()
    procs = [debug.render_stacks(f"driver:{os.getpid()}")]
    for n in _gcs_call("get_nodes"):
        async def fetch(addr=tuple(n["address"])):
            client = RpcClient(*addr)
            await client.connect(timeout=5)
            try:
                return await client.call("dump_stacks", timeout=15)
            finally:
                await client.close()

        try:
            reply = core.io.run(fetch(), timeout=20)
        except Exception:
            continue
        procs.extend(p for p in reply.get("processes", ())
                     if isinstance(p, dict))
    return procs


def summary() -> Dict:
    nodes = list_nodes()
    actors = list_actors()
    out = {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_draining": sum(1 for n in nodes
                              if n["alive"] and n.get("draining")),
        "nodes_total": len(nodes),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
        "placement_groups": len(list_placement_groups()),
        "cluster_resources": _sum_resources(nodes, "resources"),
        "available_resources": _sum_resources(
            [n for n in nodes if n["alive"]], "available"),
    }
    try:
        wg = wait_graph()
        out["stalled_tasks"] = wg.get("stalled_tasks", 0)
        out["deadlocks"] = wg.get("deadlocks", 0)
    except Exception:
        # Older GCS without the wait-graph plane: leave the keys out
        # rather than fail the whole summary.
        pass
    stats = node_stats()
    if stats:
        out["object_store_used"] = sum(
            s.get("object_store_used", 0) for s in stats)
        out["object_store_capacity"] = sum(
            s.get("object_store_capacity", 0) for s in stats)
        out["spilled_bytes"] = sum(
            s.get("spilled_bytes", 0) for s in stats)
    try:
        tes = _gcs_call("task_event_stats")
        out["task_events_dropped"] = tes.get("events_dropped_total", 0)
        out["task_event_shards"] = tes.get("shards", 0)
    except Exception:
        # Older GCS without the sharded task-event plane: leave the keys
        # out rather than fail the whole summary.
        pass
    try:
        llm = llm_serving_summary()
        if llm:
            out["llm_serving"] = llm
    except Exception:
        pass  # no metrics plane / no LLM replicas: leave the key out
    try:
        ingest = data_ingest_summary()
        if ingest:
            out["data_ingest"] = ingest
    except Exception:
        pass  # no metrics plane / nothing streamed: leave the key out
    try:
        alerts = cluster_alerts()
        out["alerts"] = {
            "firing": alerts.get("firing", []),
            "rules": len(alerts.get("rules", [])),
        }
    except Exception:
        # Older GCS without the alert evaluator: leave the key out.
        pass
    return out


def llm_serving_summary() -> Dict:
    """Fleet-wide LLM serving rollup from each replica's pushed gauges
    (the same engine_stats() numbers the router consumes)."""
    import json

    snapshots = []
    for key in _gcs_call("kv_keys", prefix=b"metrics:")["keys"]:
        reply = _gcs_call("kv_get", key=key)
        if reply.get("value"):
            snapshots.append(json.loads(reply["value"]))
    return _aggregate_llm_metrics(snapshots)


def data_ingest_summary() -> Dict:
    """Fleet-wide streaming data-plane rollup from pushed metric
    snapshots (data/streaming.py producers on every process): blocks
    pulled, backpressure engagements, live ring backlog, and total/mean
    consumer input-wait — the number that says whether ingestion hid
    behind compute. Empty dict when nothing has streamed yet."""
    import json

    blocks = backpressure = backlog = wait_sum = 0.0
    wait_count = 0
    for key in _gcs_call("kv_keys", prefix=b"metrics:")["keys"]:
        reply = _gcs_call("kv_get", key=key)
        if not reply.get("value"):
            continue
        for metric in json.loads(reply["value"]):
            name = metric.get("name", "")
            if name == "ray_tpu_data_blocks_produced_total":
                blocks += sum(metric.get("values", {}).values())
            elif name == "ray_tpu_data_backpressure_total":
                backpressure += sum(metric.get("values", {}).values())
            elif name == "ray_tpu_data_backlog_depth":
                backlog += sum(metric.get("values", {}).values())
            elif name == "ray_tpu_data_input_wait_ms":
                for h in metric.get("histograms", {}).values():
                    wait_sum += h.get("sum", 0.0)
                    wait_count += int(h.get("count", 0))
    if not blocks and not wait_count:
        return {}
    out = {"blocks_produced": int(blocks),
           "backpressure_engagements": int(backpressure),
           "backlog_depth": int(backlog),
           "batches_consumed": wait_count,
           "input_wait_ms_total": round(wait_sum, 1)}
    if wait_count:
        out["input_wait_ms_mean"] = round(wait_sum / wait_count, 3)
    return out


_BREAKDOWN_METRICS = {
    "ray_tpu_llm_ttft_breakdown_ms": "ttft_breakdown_ms",
    "ray_tpu_llm_itl_breakdown_ms": "itl_breakdown_ms",
}


def _aggregate_llm_metrics(snapshots: List[List[dict]]) -> Dict:
    """Pure rollup over per-process metric snapshots (util/metrics.py
    snapshot_all() lists): sums every ray_tpu_llm_* gauge series across
    replicas and counts the distinct replica tags seen. The per-request
    latency-breakdown histograms get a phase-aware rollup instead — their
    `values` entries are per-phase running means, and summing means
    across phases/replicas would be meaningless — so they surface as
    {phase: mean_ms} maps weighted by observation count, with a p99
    sibling map reconstructed from the merged bucket counts (the shared
    `util.metrics.histogram_quantile` helper)."""
    import json

    from ray_tpu.util.metrics import histogram_quantile

    sums: Dict[str, float] = {}
    breakdown: Dict[str, Dict[str, list]] = {}
    replicas = set()
    for snap in snapshots:
        for metric in snap:
            name = metric.get("name", "")
            if not name.startswith("ray_tpu_llm_"):
                continue
            if name in _BREAKDOWN_METRICS:
                dest = breakdown.setdefault(_BREAKDOWN_METRICS[name], {})
                boundaries = metric.get("boundaries") or []
                for tag_key, h in metric.get("histograms", {}).items():
                    phase = "?"
                    try:
                        phase = dict(json.loads(tag_key)).get("phase", "?")
                    except Exception:
                        pass
                    acc = dest.setdefault(phase, [0.0, 0, [], boundaries])
                    acc[0] += h.get("sum", 0.0)
                    acc[1] += int(h.get("count", 0))
                    buckets = h.get("buckets") or []
                    if not acc[2]:
                        acc[2] = [0] * len(buckets)
                    if len(buckets) == len(acc[2]):
                        acc[2] = [a + b for a, b in zip(acc[2], buckets)]
                continue
            short = name[len("ray_tpu_llm_"):]
            for tag_key, value in metric.get("values", {}).items():
                if "replica" in tag_key:
                    replicas.add(tag_key)
                sums[short] = sums.get(short, 0.0) + value
    if not sums and not breakdown:
        return {}
    out = {k: round(v, 1) for k, v in sums.items()}
    for key, phases in breakdown.items():
        rolled = {p: round(s / c, 3)
                  for p, (s, c, _b, _bd) in phases.items() if c}
        if rolled:
            out[key] = rolled
        p99 = {}
        for p, (_s, c, buckets, boundaries) in phases.items():
            if not c:
                continue
            q = histogram_quantile(boundaries, buckets, 0.99)
            if q is not None:
                p99[p] = round(q, 3)
        if p99:
            out[key.replace("_ms", "_p99_ms")] = p99
    out["replicas_reporting"] = len(replicas)
    return out


def _sum_resources(nodes: List[dict], key: str) -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes:
        for k, v in n[key].items():
            total[k] = total.get(k, 0.0) + v
    return total
