"""Dashboard head: REST/JSON API, Prometheus metrics, job submission.

Reference analog: python/ray/dashboard/ (DashboardHead head.py:62, aiohttp
server) with the job module (dashboard/modules/job/ — REST submit ->
supervisor) and the metrics module. One process per cluster, typically on
the head node; all cluster state comes from the GCS over RPC.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import time
import uuid
from collections import deque
from typing import Dict, Optional

from aiohttp import web

from ray_tpu.runtime.rpc import RpcClient

logger = logging.getLogger(__name__)

JOB_KV_PREFIX = b"jobsub:"


def _id_str(v) -> str:
    return v.hex() if isinstance(v, bytes) else str(v or "")


def _json(data, status=200):
    return web.Response(text=json.dumps(data, default=_coerce), status=status,
                        content_type="application/json")


def _coerce(o):
    if isinstance(o, bytes):
        return o.hex()
    return str(o)


class JobManager:
    """Drives submitted entrypoint commands as driver subprocesses.

    Reference analog: dashboard/modules/job/job_manager.py (supervisor actor
    running the entrypoint shell command); ours runs the driver directly in
    the dashboard process's node, with status durably in the GCS KV so the
    state API and CLI can list jobs from anywhere."""

    def __init__(self, gcs: RpcClient, gcs_address: str, session_dir: str):
        self.gcs = gcs
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.procs: Dict[str, subprocess.Popen] = {}

    def _log_path(self, job_id: str) -> str:
        return os.path.join(self.session_dir, "logs", f"job-{job_id}.log")

    async def _set(self, job_id: str, info: dict):
        await self.gcs.call("kv_put", key=JOB_KV_PREFIX + job_id.encode(),
                            value=json.dumps(info).encode())

    async def get(self, job_id: str) -> Optional[dict]:
        reply = await self.gcs.call("kv_get", key=JOB_KV_PREFIX + job_id.encode())
        blob = reply.get("value")
        return json.loads(blob) if blob else None

    async def list(self) -> list:
        keys = (await self.gcs.call("kv_keys", prefix=JOB_KV_PREFIX))["keys"]
        out = []
        for k in keys:
            reply = await self.gcs.call("kv_get", key=k)
            if reply.get("value"):
                out.append(json.loads(reply["value"]))
        return out

    async def submit(self, entrypoint: str, *, submission_id: Optional[str] = None,
                     env: Optional[Dict[str, str]] = None,
                     working_dir: Optional[str] = None,
                     metadata: Optional[dict] = None) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        info = {"submission_id": job_id, "entrypoint": entrypoint,
                "status": "PENDING", "start_time": time.time(),
                "end_time": None, "metadata": metadata or {},
                "message": "", "log_path": self._log_path(job_id)}
        await self._set(job_id, info)
        run_env = dict(os.environ)
        run_env.update(env or {})
        # The entrypoint's ray_tpu.init() attaches to this cluster; it must
        # also resolve this framework's import path even when the submitter
        # relied on sys.path rather than PYTHONPATH.
        run_env["RAY_TPU_ADDRESS"] = self.gcs_address
        run_env["PYTHONPATH"] = ":".join(
            [p for p in sys.path if p] +
            ([run_env["PYTHONPATH"]] if run_env.get("PYTHONPATH") else []))
        os.makedirs(os.path.dirname(self._log_path(job_id)), exist_ok=True)
        log_file = open(self._log_path(job_id), "wb")
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, cwd=working_dir or None, env=run_env,
                stdout=log_file, stderr=subprocess.STDOUT,
                start_new_session=True)
        except OSError as e:
            info.update(status="FAILED", message=repr(e), end_time=time.time())
            await self._set(job_id, info)
            return job_id
        finally:
            log_file.close()
        self.procs[job_id] = proc
        info["status"] = "RUNNING"
        await self._set(job_id, info)
        asyncio.ensure_future(self._wait(job_id, proc))
        return job_id

    async def _wait(self, job_id: str, proc: subprocess.Popen):
        while proc.poll() is None:
            await asyncio.sleep(0.5)
        info = await self.get(job_id) or {}
        if info.get("status") == "STOPPED":
            return
        info["status"] = "SUCCEEDED" if proc.returncode == 0 else "FAILED"
        if proc.returncode != 0:
            info["message"] = f"entrypoint exited with code {proc.returncode}"
        info["end_time"] = time.time()
        await self._set(job_id, info)

    async def stop(self, job_id: str) -> bool:
        proc = self.procs.get(job_id)
        info = await self.get(job_id)
        if info is None:
            return False
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except Exception:
                proc.terminate()
        info.update(status="STOPPED", end_time=time.time())
        await self._set(job_id, info)
        return True

    def logs(self, job_id: str) -> str:
        try:
            with open(self._log_path(job_id), "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""


class DashboardHead:
    def __init__(self, gcs_address: str, session_dir: str,
                 host: str = "127.0.0.1", port: int = 0):
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.host = host
        self.port = port
        self.gcs: Optional[RpcClient] = None
        self.jobs: Optional[JobManager] = None
        self._runner = None
        self._log_client: Optional[RpcClient] = None
        # (node_id, file) -> ring of recent lines. The log monitor ships
        # every node's worker log lines over GCS pubsub; the head buffers
        # the tail so the SPA can show per-worker logs without touching
        # worker filesystems (reference: dashboard log view over the
        # log_monitor channel, python/ray/dashboard/modules/log/).
        self._log_buffers: Dict[tuple, deque] = {}
        self._log_buffer_lines = 1000
        self._log_buffer_streams = 256

    async def _subscribe_logs(self):
        from ray_tpu.runtime.log_monitor import LOG_CHANNEL

        async def on_push(method, data):
            if method != "pubsub" or data.get("channel") != LOG_CHANNEL:
                return
            msg = data["message"]
            key = (msg["node_id"], msg["file"])
            buf = self._log_buffers.get(key)
            if buf is None:
                # Bound TOTAL streams, not just lines-per-stream: worker
                # churn would otherwise pin 1000 lines per worker EVER
                # seen. LRU by last write (dict insertion order; we
                # re-insert on update below).
                while len(self._log_buffers) >= self._log_buffer_streams:
                    self._log_buffers.pop(
                        next(iter(self._log_buffers)), None)
                buf = deque(maxlen=self._log_buffer_lines)
            else:
                del self._log_buffers[key]  # re-insert = move to MRU end
            buf.extend(msg["lines"])
            self._log_buffers[key] = buf

        async def _resubscribe(client):
            await client._call_once("subscribe", 30,
                                    dict(channels=[LOG_CHANNEL]))

        gcs_host, gcs_port = self.gcs_address.rsplit(":", 1)
        self._log_client = RpcClient(gcs_host, int(gcs_port),
                                     on_push=on_push, auto_reconnect=True,
                                     on_reconnect=_resubscribe)
        await self._log_client.connect(timeout=30)
        await self._log_client.call("subscribe", channels=[LOG_CHANNEL])

    async def start(self):
        gcs_host, gcs_port = self.gcs_address.rsplit(":", 1)
        self.gcs = RpcClient(gcs_host, int(gcs_port))
        await self.gcs.connect(timeout=30)
        try:
            await self._subscribe_logs()
        except Exception:
            logger.warning("worker-log streaming unavailable", exc_info=True)
        self.jobs = JobManager(self.gcs, self.gcs_address, self.session_dir)
        app = web.Application()
        app.add_routes([
            web.get("/", self.index),
            web.get("/api/version", self.version),
            web.get("/api/nodes", self.nodes),
            web.get("/api/nodes/{node_id}", self.node_detail),
            web.get("/api/actors", self.actors),
            web.get("/api/actors/{actor_id}", self.actor_detail),
            web.get("/api/timeline", self.timeline),
            web.get("/api/requests", self.requests_view),
            web.get("/api/placement_groups", self.placement_groups),
            web.get("/api/cluster_resources", self.cluster_resources),
            web.get("/api/serve", self.serve_deployments),
            web.get("/api/tasks", self.tasks),
            web.get("/api/tasks/{task_id}", self.task_detail),
            web.get("/api/events", self.events),
            web.get("/api/metrics/history", self.metrics_history_view),
            web.get("/api/alerts", self.alerts_view),
            web.get("/api/link_utilization", self.link_utilization_view),
            web.get("/api/stacks", self.stacks),
            web.get("/api/wait_graph", self.wait_graph_view),
            web.get("/metrics", self.metrics),
            web.post("/api/jobs/", self.job_submit),
            web.get("/api/jobs/", self.job_list),
            web.get("/api/jobs/{job_id}", self.job_get),
            web.get("/api/jobs/{job_id}/logs", self.job_logs),
            web.post("/api/jobs/{job_id}/stop", self.job_stop),
            web.get("/api/logs", self.logs_index),
            web.get("/api/logs/{node_id}/{fname}", self.logs_tail),
            web.static("/static", os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "static")),
        ])
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        logger.info("dashboard listening on %s:%d", self.host, self.port)
        return self

    async def close(self):
        if self._runner is not None:
            await self._runner.cleanup()
        if self._log_client is not None:
            await self._log_client.close()
        if self.gcs is not None:
            await self.gcs.close()

    # -- handlers ----------------------------------------------------------
    async def index(self, request):
        """The dashboard UI: a dependency-free single page polling the REST
        surface (the reference ships a React frontend; same information)."""
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "static", "index.html")
        with open(path, encoding="utf-8") as f:
            return web.Response(text=f.read(), content_type="text/html")

    async def tasks(self, request):
        try:
            limit = int(request.query.get("limit", "200"))
        except ValueError:
            return _json({"error": "limit must be an integer"}, status=400)
        return _json(await self.gcs.call(
            "list_tasks", state=request.query.get("state"),
            name=request.query.get("name"), limit=limit))

    async def task_detail(self, request):
        """Task drill-through: full state-transition history of one task
        (reference: the dashboard's task page)."""
        return _json(await self.gcs.call(
            "get_task", task_id_hex=request.match_info["task_id"]))

    async def version(self, request):
        import ray_tpu
        return _json({"version": ray_tpu.__version__})

    async def nodes(self, request):
        return _json(await self.gcs.call("get_nodes", only_alive=False))

    async def actors(self, request):
        return _json(await self.gcs.call("list_actors"))

    async def node_detail(self, request):
        """Node drill-down: full record + the actors placed on it (the
        reference dashboard's node page)."""
        node_id = request.match_info["node_id"]
        nodes = await self.gcs.call("get_nodes", only_alive=False)
        # GCS returns raw bytes ids in-process; URLs carry hex prefixes.
        node = next((n for n in nodes
                     if _id_str(n["node_id"]).startswith(node_id)), None)
        if node is None:
            return _json({"error": f"no node {node_id}"}, status=404)
        actors = await self.gcs.call("list_actors")
        node["actors"] = [
            a for a in actors
            if _id_str(a.get("node_id") or b"") == _id_str(node["node_id"])]
        return _json(node)

    async def actor_detail(self, request):
        """Actor drill-down: full record + its recent task transitions."""
        actor_id = request.match_info["actor_id"]
        actors = await self.gcs.call("list_actors")
        actor = next((a for a in actors
                      if _id_str(a["actor_id"]).startswith(actor_id)), None)
        if actor is None:
            return _json({"error": f"no actor {actor_id}"}, status=404)
        aid_hex = _id_str(actor["actor_id"])
        events = await self.gcs.call("task_timeline", limit=5000)
        actor["task_events"] = [
            e for e in events
            if _id_str(e.get("actor_id") or b"") == aid_hex][-200:]
        return _json(actor)

    async def timeline(self, request):
        """Execution bars for the timeline view: RUNNING..FINISHED/FAILED
        pairs per task, laned by executing worker (`ray timeline` /
        chrome-trace analog; /api/timeline?format=chrome downloads a
        chrome://tracing-loadable JSON)."""
        try:
            limit = int(request.query.get("limit", "2000"))
            if limit <= 0:
                raise ValueError
        except ValueError:
            return _json({"error": "limit must be a positive integer"},
                         status=400)
        events = await self.gcs.call("task_timeline", limit=limit)
        # Pair by task_id, tolerating any arrival/clock order: driver
        # batches (SUBMITTED/FINISHED) interleave with worker batches
        # (RUNNING), and inter-node clock skew can even put a FINISHED
        # stamp before its RUNNING stamp.
        open_at: dict = {}
        done_at: dict = {}
        bars = []

        def close(start, end_ev):
            bars.append({
                "task_id": start["task_id"], "name": end_ev["name"],
                "worker": start.get("worker") or "?",
                "start": start["time"],
                "end": max(end_ev["time"], start["time"]),  # skew clamp
                "ok": end_ev["state"] == "FINISHED",
                "actor_id": end_ev.get("actor_id"),
            })

        for ev in sorted(events, key=lambda e: e["time"]):
            tid = ev["task_id"]
            if ev["state"] == "RUNNING":
                if tid in done_at:
                    close(ev, done_at.pop(tid))
                else:
                    open_at[tid] = ev
            elif ev["state"] in ("FINISHED", "FAILED"):
                if tid in open_at:
                    close(open_at.pop(tid), ev)
                else:
                    done_at[tid] = ev  # RUNNING may arrive later (skew)
        now = time.time()
        for start in open_at.values():  # still running: open-ended bar
            bars.append({
                "task_id": start["task_id"], "name": start["name"],
                "worker": start.get("worker") or "?",
                "start": start["time"], "end": max(now, start["time"]),
                "ok": None, "actor_id": start.get("actor_id"),
            })
        if request.query.get("format") == "chrome":
            trace = [{
                "name": b["name"], "ph": "X", "ts": b["start"] * 1e6,
                "dur": (b["end"] - b["start"]) * 1e6,
                "pid": "ray_tpu", "tid": b["worker"],
                "args": {"task_id": b["task_id"]},
            } for b in bars]
            return _json({"traceEvents": trace})
        return _json(bars)

    async def requests_view(self, request):
        """Stitched per-request serving trace (`scripts request` analog):
        /api/requests?id=<request_id> pulls every process's span ring —
        the raylets fan `dump_spans` out to their workers — and returns
        the spans whose trace id derives from that request id, sorted by
        start time. The trace id is a pure function of the request id, so
        no propagation state is needed here."""
        from ray_tpu.runtime.rpc import RpcClient
        from ray_tpu.util import tracing

        rid = request.query.get("id")
        if not rid:
            return _json({"error": "missing ?id=<request_id>"}, status=400)
        want = tracing.request_trace_id(rid).hex()
        groups = [("dashboard", tracing.get_spans())]
        for n in await self.gcs.call("get_nodes"):
            try:
                client = RpcClient(*tuple(n["address"]))
                await client.connect(timeout=5)
                try:
                    reply = await client.call("dump_spans", timeout=15)
                finally:
                    await client.close()
            except Exception:
                continue
            for proc in reply.get("processes", ()):
                groups.append((proc["label"], proc["spans"]))
        spans, seen = [], set()
        for label, group in groups:
            for s in group:
                a = s.get("args") or {}
                if a.get("trace_id") != want:
                    continue
                sid = a.get("span_id")
                if sid and sid in seen:
                    continue  # same ring reachable via two fan-out paths
                seen.add(sid)
                ev = dict(s)
                ev["process"] = label
                spans.append(ev)
        spans.sort(key=lambda s: s.get("ts", 0.0))
        return _json({"request_id": rid, "trace_id": want, "spans": spans})

    async def placement_groups(self, request):
        return _json(await self.gcs.call("list_placement_groups"))

    async def cluster_resources(self, request):
        nodes = await self.gcs.call("get_nodes")
        total, avail = {}, {}
        for n in nodes:
            for k, v in n.get("resources", {}).items():
                total[k] = total.get(k, 0.0) + v
            for k, v in n.get("available", {}).items():
                avail[k] = avail.get(k, 0.0) + v
        return _json({"total": total, "available": avail})

    async def serve_deployments(self, request):
        """Serve deployments view: the controller snapshots its state into
        the GCS KV on every change (reference: dashboard serve module)."""
        import json as json_mod

        reply = await self.gcs.call("kv_get", key=b"serve:deployments")
        blob = reply.get("value")
        return _json({"deployments":
                      json_mod.loads(blob) if blob else []})

    async def events(self, request):
        """Typed cluster events (runtime/events.py), newest first; filters
        mirror the `scripts events` CLI: ?type=, ?severity=, ?source=,
        ?limit=."""
        try:
            limit = int(request.query.get("limit", "100"))
        except ValueError:
            return _json({"error": "limit must be an integer"}, status=400)
        events = await self.gcs.call(
            "list_events", event_type=request.query.get("type"),
            severity=request.query.get("severity"),
            source=request.query.get("source"), limit=limit)
        return _json({"events": events})

    async def metrics_history_view(self, request):
        """Windowed queries over the GCS metric-history rings (`state.
        metrics_history` twin): ?name=<series> [&window=N] [&agg=rate|
        delta|mean|last|p99...] [&tags=k:v,k:v] — returns the aggregate
        value, the per-node split, and per-reporter point tails the
        Metrics view renders as sparklines."""
        name = request.query.get("name")
        if not name:
            return _json({"error": "name query param required"}, status=400)
        try:
            window_s = float(request.query.get("window", "60"))
            points_limit = int(request.query.get("points", "240"))
        except ValueError:
            return _json({"error": "window/points must be numeric"},
                         status=400)
        tags = None
        raw = request.query.get("tags")
        if raw:
            try:
                tags = dict(kv.split(":", 1) for kv in raw.split(","))
            except ValueError:
                return _json({"error": "tags must be k:v[,k:v...]"},
                             status=400)
        try:
            reply = await self.gcs.call(
                "metrics_history", name=name, tags=tags, window_s=window_s,
                agg=request.query.get("agg"), points_limit=points_limit)
        except Exception as e:
            return _json({"error": str(e)}, status=400)
        return _json(reply)

    async def alerts_view(self, request):
        """Alert-rule states from the GCS alert evaluator (runtime/
        alert_defs.py): every rule with state ok/firing, last value, and
        since — the header badge + alerts strip data source."""
        return _json(await self.gcs.call("list_alerts"))

    async def link_utilization_view(self, request):
        """Observed per-link bandwidth matrix from the tagged collective
        byte counters in the history rings (?window=N, default 30s)."""
        try:
            window_s = float(request.query.get("window", "30"))
        except ValueError:
            return _json({"error": "window must be numeric"}, status=400)
        return _json(await self.gcs.call("link_utilization",
                                         window_s=window_s))

    async def stacks(self, request):
        """Cluster-wide annotated stack dumps (`scripts stack --cluster`
        analog): every raylet fans the `dump_stacks` RPC out to its
        workers; unreachable nodes are skipped. ?format=text renders the
        deduped text view, default is the structured JSON."""
        from ray_tpu.runtime.rpc import RpcClient
        from ray_tpu.utils import debug

        procs = [debug.render_stacks("dashboard")]
        for n in await self.gcs.call("get_nodes"):
            try:
                client = RpcClient(*tuple(n["address"]))
                await client.connect(timeout=5)
                try:
                    reply = await client.call("dump_stacks", timeout=15)
                finally:
                    await client.close()
            except Exception:
                continue
            procs.extend(p for p in reply.get("processes", ())
                         if isinstance(p, dict))
        if request.query.get("format") == "text":
            return web.Response(text=debug.format_stacks(procs),
                                content_type="text/plain")
        return _json({"processes": procs})

    async def wait_graph_view(self, request):
        """The GCS-assembled cluster wait-graph + stall/deadlock detector
        verdict (edges, cycles, stalled_tasks, deadlocks)."""
        return _json(await self.gcs.call("wait_graph"))

    async def metrics(self, request):
        """Aggregate app metrics pushed to the KV by util.metrics plus a few
        built-in cluster gauges, in Prometheus text format. Only snapshots
        from ALIVE nodes count: `metrics:<node>:<pid>` keys from dead
        processes would otherwise inflate counters forever (the GCS also
        purges them on node death; this filter covers keys raced in after
        the purge)."""
        from ray_tpu.util.metrics import prometheus_text

        nodes = await self.gcs.call("get_nodes", only_alive=False)
        alive_hex = {n["node_id"].hex() for n in nodes
                     if n.get("alive", True)}
        snapshots = []
        keys = (await self.gcs.call("kv_keys", prefix=b"metrics:"))["keys"]
        for k in keys:
            parts = k.decode(errors="replace").split(":")
            # Keep keys whose node isn't in the node table (e.g. a driver
            # that flushed before node assignment records "unknown").
            if len(parts) >= 2 and parts[1] not in alive_hex \
                    and any(n["node_id"].hex() == parts[1] for n in nodes):
                continue
            reply = await self.gcs.call("kv_get", key=k)
            if reply.get("value"):
                try:
                    snapshots.extend(json.loads(reply["value"]))
                except Exception:
                    continue
        alive = len(alive_hex)
        builtin = [
            {"name": "ray_tpu_cluster_nodes", "type": "gauge",
             "description": "alive nodes", "values": {"[]": float(alive)}},
        ]
        text = prometheus_text(builtin + snapshots)
        return web.Response(text=text, content_type="text/plain")

    # -- job API (dashboard/modules/job REST surface) ----------------------
    async def job_submit(self, request):
        body = await request.json()
        if "entrypoint" not in body:
            return _json({"error": "entrypoint required"}, status=400)
        job_id = await self.jobs.submit(
            body["entrypoint"],
            submission_id=body.get("submission_id"),
            env=(body.get("runtime_env") or {}).get("env_vars"),
            working_dir=(body.get("runtime_env") or {}).get("working_dir"),
            metadata=body.get("metadata"))
        return _json({"submission_id": job_id})

    async def job_list(self, request):
        return _json(await self.jobs.list())

    async def job_get(self, request):
        info = await self.jobs.get(request.match_info["job_id"])
        if info is None:
            return _json({"error": "no such job"}, status=404)
        return _json(info)

    async def job_logs(self, request):
        return _json({"logs": self.jobs.logs(request.match_info["job_id"])})

    async def logs_index(self, request):
        """Per-node worker log files the head has buffered (from the log
        monitor's pubsub stream), with line counts."""
        nodes: Dict[str, list] = {}
        for (node_id, fname), buf in sorted(self._log_buffers.items()):
            nodes.setdefault(node_id, []).append(
                {"file": fname, "lines": len(buf)})
        return _json({"nodes": nodes})

    async def logs_tail(self, request):
        node_id = request.match_info["node_id"]
        fname = request.match_info["fname"]
        try:
            tail = int(request.query.get("tail", "200"))
        except ValueError:
            tail = 200
        buf = self._log_buffers.get((node_id, fname))
        if buf is None:
            return _json({"error": "no such log stream"}, status=404)
        lines = list(buf)
        if tail > 0:
            lines = lines[-tail:]
        return _json({"node_id": node_id, "file": fname, "lines": lines,
                      "buffered": len(buf)})

    async def job_stop(self, request):
        ok = await self.jobs.stop(request.match_info["job_id"])
        return _json({"stopped": ok})


async def _amain(argv):
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8265)
    args = parser.parse_args(argv)
    head = DashboardHead(args.gcs_address, args.session_dir,
                         args.host, args.port)
    await head.start()
    print(json.dumps({"port": head.port}), flush=True)
    while True:
        await asyncio.sleep(3600)


def main():
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_amain(sys.argv[1:]))


if __name__ == "__main__":
    main()
