/* ray_tpu dashboard SPA (reference analog: python/ray/dashboard/client/).
   Hash-routed views over the REST surface; no build step, no dependencies.
   Charts: single-axis SVG line charts, 2px strokes, legend + direct end
   labels (identity is never color-alone), crosshair + tooltip hover. */

const $ = (id) => document.getElementById(id);
const esc = (s) => String(s).replace(/[&<>"']/g,
  (c) => ({"&": "&amp;", "<": "&lt;", ">": "&gt;",
           '"': "&quot;", "'": "&#39;"}[c]));
const short = (s) => esc(String(s).slice(0, 12));
const state = (s) =>
  `<span class="${/ALIVE|alive|RUNNING|SUCCEEDED|FINISHED|HEALTHY|ok/
    .test(s) ? "ok" : "bad"}">${esc(s)}</span>`;
const SERIES = ["#5992e6", "#1da666", "#c0850c", "#ca598c"]; // validated

async function j(url, opts) {
  const r = await fetch(url, opts);
  if (!r.ok) throw new Error(`${url}: HTTP ${r.status}`);
  return r.json();
}

function rows(head, data, fn) {
  return `<table><tr>${head.map((h) => `<th>${h}</th>`).join("")}</tr>` +
    data.map((d) =>
      `<tr>${fn(d).map((c) => `<td>${c}</td>`).join("")}</tr>`).join("") +
    "</table>";
}

function tiles(items) {
  return `<div class="tile-row">` + items.map(([k, v, cls]) =>
    `<div class="tile"><div class="v ${cls || ""}">${v}</div>` +
    `<div class="k">${esc(k)}</div></div>`).join("") + "</div>";
}

// ---------------------------------------------------------------- charts

/** Single-axis line chart with legend, direct end labels, crosshair
 * tooltip. series: [{name, color, points:[{t, v}]}], v may be null.
 * Hover data lives in CHART_DATA keyed by `key` (stable per chart) —
 * never serialized into the DOM. */
const CHART_DATA = new Map();

function lineChart(key, series,
                   {h = 160, ymax = null, fmt = (v) => v} = {}) {
  const W = 600, H = h, padL = 34, padR = 70, padT = 8, padB = 16;
  const all = series.flatMap((s) => s.points.filter((p) => p.v != null));
  if (!all.length) return `<span class="muted">no data yet</span>`;
  const t0 = Math.min(...all.map((p) => p.t));
  const t1 = Math.max(...all.map((p) => p.t));
  const vmax = ymax ?? Math.max(...all.map((p) => p.v), 1e-9) * 1.05;
  const x = (t) => padL + (W - padL - padR) * (t - t0) / Math.max(t1 - t0, 1e-9);
  const y = (v) => padT + (H - padT - padB) * (1 - v / vmax);
  const gridVals = [0, vmax / 2, vmax];
  const grid = gridVals.map((v) =>
    `<line class="gridline" x1="${padL}" x2="${W - padR}" y1="${y(v)}" y2="${y(v)}"/>` +
    `<text x="2" y="${y(v) + 3}">${fmt(v)}</text>`).join("");
  const polys = series.map((s, i) => {
    const pts = s.points.filter((p) => p.v != null)
      .map((p) => `${x(p.t).toFixed(1)},${y(p.v).toFixed(1)}`).join(" ");
    if (!pts) return "";
    const last = s.points.filter((p) => p.v != null).at(-1);
    // direct end label: identity is not carried by color alone
    return `<polyline class="series" stroke="${s.color}" points="${pts}"/>` +
      `<text x="${W - padR + 4}" y="${y(last.v) + 3}" fill="${s.color}">` +
      `${esc(s.name)}</text>`;
  }).join("");
  CHART_DATA.set(key, {series, t0, t1, vmax, padL, padR, padT, padB, W, H});
  const legend = series.length > 1
    ? `<div class="legend">` + series.map((s) =>
        `<span><span class="swatch" style="background:${s.color}"></span>` +
        `${esc(s.name)}</span>`).join("") + "</div>"
    : "";
  return `<svg class="chart hoverable" viewBox="0 0 ${W} ${H}" width="100%"` +
    ` height="${H}" preserveAspectRatio="none" data-chart="${esc(key)}">` +
    grid + polys + `<g class="hoverlayer"></g></svg>` + legend;
}

// crosshair + tooltip on chart hover
document.addEventListener("mousemove", (e) => {
  const svg = e.target.closest?.("svg.hoverable");
  const tip = $("tooltip");
  if (!svg) { tip.hidden = true; document.querySelectorAll(".hoverlayer")
      .forEach((g) => g.innerHTML = ""); return; }
  const d = CHART_DATA.get(svg.dataset.chart);
  if (!d) { tip.hidden = true; return; }
  const rect = svg.getBoundingClientRect();
  const fx = (e.clientX - rect.left) / rect.width * d.W;
  const t = d.t0 + (fx - d.padL) / Math.max(d.W - d.padL - d.padR, 1) *
    (d.t1 - d.t0);
  const lines = d.series.map((s) => {
    let best = null;
    for (const p of s.points)
      if (p.v != null && (!best || Math.abs(p.t - t) < Math.abs(best.t - t)))
        best = p;
    return best && {name: s.name, color: s.color, ...best};
  }).filter(Boolean);
  if (!lines.length) { tip.hidden = true; return; }
  const xpix = d.padL + (d.W - d.padL - d.padR) *
    (lines[0].t - d.t0) / Math.max(d.t1 - d.t0, 1e-9);
  svg.querySelector(".hoverlayer").innerHTML =
    `<line class="crosshair" x1="${xpix}" x2="${xpix}" y1="${d.padT}"` +
    ` y2="${d.H - d.padB}"/>`;
  tip.innerHTML =
    `<div class="muted">${new Date(lines[0].t).toLocaleTimeString()}</div>` +
    lines.map((l) => `<div class="row"><span>` +
      `<span class="swatch" style="background:${l.color};display:inline-block;` +
      `width:8px;height:8px;border-radius:2px;margin-right:4px"></span>` +
      `${esc(l.name)}</span><b>${(+l.v).toFixed(3)}</b></div>`).join("");
  tip.hidden = false;
  tip.style.left = Math.min(e.clientX + 14, innerWidth - 180) + "px";
  tip.style.top = (e.clientY + 14) + "px";
});

// ---------------------------------------------------- data + history

const snapshot = {nodes: [], actors: [], pgs: [], jobs: [], tasks: [],
                  serve: {deployments: []}, res: {total: {}, available: {}},
                  metricsText: ""};
const history = {util: [], metrics: new Map()};  // ring buffers
const HIST_MAX = 300;

function parsePrometheus(text) {
  // name{labels} value  -> aggregate by family (sum), keep help text
  const fams = new Map();
  let help = {};
  for (const line of text.split("\n")) {
    if (line.startsWith("# HELP ")) {
      const [, name, ...rest] = line.slice(7).split(" ");
      help[name] = rest.join(" ");
      continue;
    }
    if (!line || line.startsWith("#")) continue;
    const m = line.match(/^([a-zA-Z_:][\w:]*)(\{.*\})?\s+([-+eE.\d]+|NaN)/);
    if (!m) continue;
    const v = parseFloat(m[3]);
    if (Number.isNaN(v)) continue;
    const f = fams.get(m[1]) || {sum: 0, n: 0, help: help[m[1]] || ""};
    f.sum += v;
    f.n += 1;
    fams.set(m[1], f);
  }
  return fams;
}

async function poll() {
  const [nodes, actors, pgs, jobs, res, tasks, serve] = await Promise.all([
    j("/api/nodes"), j("/api/actors"), j("/api/placement_groups"),
    j("/api/jobs/"), j("/api/cluster_resources"), j("/api/tasks"),
    j("/api/serve")]);
  Object.assign(snapshot, {nodes, actors, pgs, jobs, res, tasks, serve});
  try {
    snapshot.metricsText = await (await fetch("/metrics")).text();
  } catch { snapshot.metricsText = ""; }
  const now = Date.now();
  const frac = (k) => {
    const t = res.total[k] || 0;
    return t ? (t - (res.available[k] ?? 0)) / t : null;
  };
  history.util.push({t: now, cpu: frac("CPU"), tpu: frac("TPU")});
  if (history.util.length > HIST_MAX) history.util.shift();
  for (const [name, fam] of parsePrometheus(snapshot.metricsText)) {
    const buf = history.metrics.get(name) ||
      {points: [], help: fam.help};
    buf.help = fam.help || buf.help;
    buf.points.push({t: now, v: fam.sum});
    if (buf.points.length > HIST_MAX) buf.points.shift();
    history.metrics.set(name, buf);
  }
  const alive = nodes.filter((n) => n.alive).length;
  $("summary").textContent =
    `${alive}/${nodes.length} nodes · ${actors.length} actors · ` +
    `${jobs.length} jobs · ${new Date().toLocaleTimeString()}`;
}

// ---------------------------------------------------------------- views

const VIEWS = {
  overview: {title: "Overview", render: renderOverview},
  nodes: {title: "Nodes", render: renderNodes},
  actors: {title: "Actors", render: renderActors},
  tasks: {title: "Tasks", render: renderTasks},
  jobs: {title: "Jobs", render: renderJobs},
  serve: {title: "Serve", render: renderServe},
  logs: {title: "Logs", render: renderLogs},
  metrics: {title: "Metrics", render: renderMetrics},
};
let logsIndex = {nodes: {}};  // /api/logs: node -> [{file, lines}]
let alertsState = null;       // /api/alerts payload (metrics view)
let serverHist = [];          // /api/metrics/history sparkline payloads
// GCS-ring-backed sparklines shown on the Metrics view: unlike the
// client-side ring (history.metrics, lost on reload), these survive
// page loads and window the server's own time series.
const SERVER_SERIES = [
  {name: "ray_tpu_tasks_finished_total", agg: "rate", unit: "ops/s"},
  {name: "ray_tpu_llm_ttft_breakdown_ms", agg: "p99", unit: "ms"},
  {name: "ray_tpu_collective_bytes_sent_total", agg: "rate", unit: "B/s"},
];
let logSel = null;            // {node, file} picked in the Logs view
let logTail = null;           // /api/logs/<node>/<file> payload
let detail = null;   // {title, body} pinned under the active view
let searchTerm = "";

function utilChart() {
  return lineChart("util", [
    {name: "CPU", color: SERIES[0],
     points: history.util.map((u) => ({t: u.t, v: u.cpu}))},
    {name: "TPU", color: SERIES[1],
     points: history.util.map((u) => ({t: u.t, v: u.tpu}))},
  ], {ymax: 1, fmt: (v) => `${Math.round(v * 100)}%`});
}

function renderOverview() {
  const s = snapshot;
  const alive = s.nodes.filter((n) => n.alive).length;
  const running = s.tasks.filter((t) => /RUNNING/.test(t.state)).length;
  return `
  <section class="wide"><h2>Cluster</h2>${tiles([
    ["nodes alive", `${alive}/${s.nodes.length}`,
     alive === s.nodes.length ? "ok" : "bad"],
    ["actors", s.actors.length],
    ["placement groups", s.pgs.length],
    ["tasks running", running],
    ["jobs", s.jobs.length],
    ["serve deployments", (s.serve.deployments || []).length],
  ])}</section>
  <section class="wide"><h2>Utilization
    <span class="right muted">used fraction, last ${
      Math.round(HIST_MAX * POLL_MS / 1000 / 60)} min</span></h2>
    ${utilChart()}</section>
  <section><h2>Cluster resources</h2>${rows(["resource", "used / total", ""],
    Object.keys(s.res.total), (k) => {
      const total = s.res.total[k], avail = s.res.available[k] ?? 0;
      const used = total - avail, pct = total ? (100 * used / total) : 0;
      const fmt = (v) => k === "memory"
        ? (v / 2 ** 30).toFixed(1) + " GiB" : +v.toFixed(2);
      return [esc(k), `${fmt(used)} / ${fmt(total)}`,
              `<div class="bar"><div style="width:${pct}%"></div></div>`];
    })}</section>
  <section><h2>Recent tasks</h2>${rows(["task", "name", "state"],
    s.tasks.slice(0, 12), (t) => [short(t.task_id), esc(t.name || ""),
                                  state(t.state)])}</section>`;
}

function renderNodes() {
  return `
  <section class="wide"><h2>Nodes</h2>${rows(
    ["node", "state", "role", "CPU avail/total", "TPU avail/total", "labels"],
    snapshot.nodes, (n) => [
      `<code class="drill" data-kind="nodes" data-id="${esc(n.node_id)}">` +
        `${short(n.node_id)}</code>`,
      state(n.alive ? "alive" : "dead"),
      n.is_head ? "head" : "worker",
      `${n.available?.CPU ?? "-"} / ${n.resources?.CPU ?? "-"}`,
      `${n.available?.TPU ?? "-"} / ${n.resources?.TPU ?? "-"}`,
      esc(Object.entries(n.labels || {}).map(([k, v]) => `${k}=${v}`)
        .join(" ")),
    ])}</section>
  <section class="wide"><h2>Placement groups</h2>${rows(
    ["pg", "name", "strategy", "state", "bundles"],
    snapshot.pgs.slice(0, 100), (p) => [
      short(p.pg_id), esc(p.name || ""), esc(p.strategy), state(p.state),
      p.bundles?.length ?? ""])}</section>
  ${detailSection()}`;
}

function renderActors() {
  const term = searchTerm.toLowerCase();
  const match = (a) => !term ||
    `${a.actor_id} ${a.class_name} ${a.state}`.toLowerCase().includes(term);
  return `
  <section class="wide"><h2>Actors
      <span class="right muted">${snapshot.actors.length} total</span></h2>
    <div class="searchbox"><input type="text" id="search"
      placeholder="filter by id / class / state" value="${esc(searchTerm)}">
    </div>
    ${rows(["actor", "class", "state", "node", "restarts", "pid"],
      snapshot.actors.filter(match).slice(0, 200), (a) => [
        `<code class="drill" data-kind="actors" data-id="${esc(a.actor_id)}">` +
          `${short(a.actor_id)}</code>`,
        esc(a.class_name || ""), state(a.state),
        `<code>${a.node_id ? short(a.node_id) : ""}</code>`,
        `${a.restarts_used}/${a.max_restarts}`, a.pid ?? ""])}</section>
  ${detailSection()}`;
}

function renderTasks() {
  return `
  <section class="wide"><h2>Timeline
    <a class="right muted linklike" href="/api/timeline?format=chrome"
       download="timeline.json">download chrome trace</a></h2>
    <div id="timeline">${timelineHtml()}</div></section>
  <section class="wide"><h2>Recent tasks</h2>${rows(
    ["task", "name", "state", "actor", "node"],
    snapshot.tasks.slice(0, 200), (t) => [
      `<a class="drill linklike" data-kind="tasks" ` +
      `data-id="${esc(String(t.task_id))}">${short(t.task_id)}</a>`,
      esc(t.name || ""), state(t.state),
      `<code>${t.actor_id ? short(t.actor_id) : ""}</code>`,
      `<code>${t.node_id ? short(t.node_id) : ""}</code>`])}</section>`;
}

let timelineBars = [];
function timelineHtml() {
  const bars = timelineBars;
  if (!bars.length) return `<span class="muted">no task spans yet</span>`;
  const t0 = Math.min(...bars.map((b) => b.start));
  const t1 = Math.max(...bars.map((b) => b.end));
  const span = Math.max(t1 - t0, 1e-6);
  const lanes = [...new Set(bars.map((b) => b.worker))].sort();
  return lanes.map((w) => {
    const r = bars.filter((b) => b.worker === w).slice(-200).map((b) => {
      const left = 100 * (b.start - t0) / span;
      const width = Math.max(100 * (b.end - b.start) / span, 0.3);
      const color = b.ok === false ? "var(--bad)"
        : b.ok === null ? "var(--dim)" : "var(--s1)";
      const dur = ((b.end - b.start) * 1000).toFixed(1);
      return `<div title="${esc(b.name)} · ${dur} ms" style="position:absolute;` +
        `left:${left}%;width:${width}%;height:10px;background:${color};` +
        `border-radius:2px"></div>`;
    }).join("");
    return `<div style="display:flex;align-items:center;gap:8px;margin:2px 0">` +
      `<code style="width:110px;flex:none;font-size:11px">${short(w)}</code>` +
      `<div style="position:relative;height:12px;flex:1">${r}</div></div>`;
  }).join("") + `<div class="muted" style="font-size:11px;margin-top:4px">` +
    `${bars.length} spans · ${(t1 - t0).toFixed(1)}s window</div>`;
}

function renderJobs() {
  return `
  <section class="wide"><h2>Submit job</h2>
    <form class="inline" id="jobform">
      <input type="text" id="entrypoint"
        placeholder='entrypoint, e.g. python -c "print(42)"'>
      <button type="submit">submit</button></form></section>
  <section class="wide"><h2>Jobs</h2>${rows(
    ["job", "status", "entrypoint", ""],
    snapshot.jobs.slice(0, 100), (jb) => [
      `<code>${esc(jb.submission_id || jb.job_id)}</code>`,
      state(jb.status || (jb.alive ? "alive" : "finished")),
      esc(jb.entrypoint || ""),
      `<a class="logs linklike muted" data-id="${
        esc(jb.submission_id || jb.job_id)}">logs</a> · ` +
      `<a class="stopjob linklike muted" data-id="${
        esc(jb.submission_id || jb.job_id)}">stop</a>`])}</section>
  ${detailSection()}`;
}

function renderServe() {
  const d = snapshot.serve;
  return `
  <section class="wide"><h2>Serve deployments</h2>${rows(
    ["deployment", "replicas", "version", "autoscaling"],
    d.deployments || [], (x) => [
      `<code>${esc(x.name)}</code>`, x.num_replicas, esc(x.version ?? ""),
      x.autoscaling ? "on" : "off"])}</section>
  ${(d.apps || []).length ? `<section class="wide"><h2>Applications</h2>${
    rows(["app", "route", "status"], d.apps, (a) =>
      [esc(a.name), esc(a.route_prefix || ""), state(a.status || "")])
    }</section>` : ""}`;
}

function renderLogs() {
  const nodes = logsIndex.nodes || {};
  const list = Object.entries(nodes).map(([node, files]) =>
    `<h3 class="muted">node ${esc(node.slice(0, 12))}</h3>` +
    files.map((f) => {
      const active = logSel && logSel.node === node &&
        logSel.file === f.file;
      return `<a href="#logs" class="logfile ${active ? "active" : ""}"` +
        ` data-node="${esc(node)}" data-file="${esc(f.file)}">` +
        `${esc(f.file)} <span class="muted">(${f.lines})</span></a>`;
    }).join("<br>")).join("");
  const tail = logTail
    ? `<h2>${esc(logTail.file)}<span class="right muted">last ` +
      `${logTail.lines.length} of ${logTail.buffered} buffered lines` +
      `</span></h2><pre class="logs">${esc(logTail.lines.join("\n"))}</pre>`
    : `<p class="muted">select a worker log stream</p>`;
  return `
  <section><h2>Worker log streams</h2>${list ||
    '<p class="muted">no log lines received yet</p>'}</section>
  <section class="wide">${tail}</section>`;
}

function renderAlerts() {
  if (!alertsState || !(alertsState.rules || []).length) return "";
  const firing = (alertsState.firing || []).length;
  return `<section class="wide"><h2>Alerts
      <span class="right ${firing ? "bad" : "muted"}">${firing} firing</span>
    </h2>${rows(["rule", "state", "value", "threshold", "summary"],
    alertsState.rules, (r) => [
      esc(r.name), state(r.state === "firing" ? "FIRING" : "ok"),
      r.value == null ? "—" : +(+r.value).toPrecision(4),
      r.threshold ?? "", `<span class="muted">${esc(r.summary || "")}</span>`,
    ])}</section>`;
}

function renderServerHistory() {
  if (!serverHist.length) return "";
  // Each payload carries per-reporter point tails from the GCS rings;
  // draw one sparkline per reporter series, value label = the windowed
  // aggregate the server computed (rate / p99 / ...).
  const charts = serverHist.map((s, i) => {
    const lines = s.hist.series.slice(0, 4).map((ser, k) => ({
      name: `${ser.reporter.slice(0, 12)} ${Object.entries(ser.tags || {})
        .map(([a, b]) => `${a}=${b}`).join(",")}`,
      color: SERIES[(i + k) % SERIES.length],
      points: ser.points.map(([t, v]) => ({t: t * 1000, v})),
    }));
    const val = s.hist.value == null ? "no samples"
      : `${s.agg} ${+(+s.hist.value).toPrecision(4)} ${s.unit}`;
    return `<section><h2>${esc(s.name)}
        <span class="right muted">${esc(val)} · 5 min window</span></h2>
      ${lineChart(`h:${s.name}`, lines, {h: 90,
                                         fmt: (v) => +v.toPrecision(3)})}
    </section>`;
  });
  return `<section class="wide"><h2>Cluster history
      <span class="right muted">GCS time-series rings ·
        /api/metrics/history</span></h2></section>` + charts.join("");
}

function renderMetrics() {
  const head = renderAlerts() + renderServerHistory();
  const fams = [...history.metrics.entries()]
    .filter(([, b]) => b.points.length > 1)
    .sort(([a], [b]) => a.localeCompare(b));
  if (!fams.length)
    return head + `<section class="wide"><h2>Metrics</h2>
      <span class="muted">no prometheus families scraped yet</span></section>`;
  const charts = fams.slice(0, 24).map(([name, buf], i) => `
    <section><h2>${esc(name)}</h2>
      <div class="muted" style="margin-bottom:4px">${esc(buf.help)}</div>
      ${lineChart(`m:${name}`,
                  [{name, color: SERIES[i % SERIES.length],
                    points: buf.points}],
                  {h: 110, fmt: (v) => +v.toPrecision(3)})}</section>`);
  return head + charts.join("") +
    (fams.length > 24 ? `<section class="wide"><span class="muted">` +
      `${fams.length - 24} more families not shown</span></section>` : "");
}

function detailSection() {
  if (!detail) return "";
  return `<section class="wide"><h2>${esc(detail.title)}
    <a class="right muted linklike" id="closedetail">close</a></h2>
    <pre class="logs">${esc(detail.body)}</pre></section>`;
}

// ------------------------------------------------------------- routing

function currentView() {
  const name = (location.hash || "#overview").slice(1);
  return VIEWS[name] ? name : "overview";
}

function renderNav() {
  const cur = currentView();
  $("nav").innerHTML = Object.entries(VIEWS).map(([name, v]) =>
    `<a href="#${name}" class="${name === cur ? "active" : ""}">` +
    `${v.title}</a>`).join("");
}

async function render() {
  renderNav();
  if (currentView() === "tasks") {
    try { timelineBars = await j("/api/timeline?limit=2000"); }
    catch { timelineBars = []; }
  }
  if (currentView() === "metrics") {
    try { alertsState = await j("/api/alerts"); } catch { alertsState = null; }
    serverHist = (await Promise.all(SERVER_SERIES.map(async (s) => {
      try {
        const hist = await j(`/api/metrics/history?name=${s.name}` +
                             `&agg=${s.agg}&window=300`);
        return {...s, hist};
      } catch { return null; }
    }))).filter((s) => s && s.hist && (s.hist.series || []).length);
  }
  if (currentView() === "logs") {
    try { logsIndex = await j("/api/logs"); } catch { logsIndex = {nodes: {}}; }
    if (logSel) {
      try {
        logTail = await j(`/api/logs/${logSel.node}/` +
                          `${encodeURIComponent(logSel.file)}?tail=500`);
      } catch { logTail = null; }
    }
  }
  const focused = document.activeElement?.id === "search";
  const pos = focused ? document.activeElement.selectionStart : 0;
  $("view").innerHTML = VIEWS[currentView()].render();
  if (focused && $("search")) {
    $("search").focus();
    $("search").setSelectionRange(pos, pos);
  }
}

async function tick(force = false) {
  if (!force && !$("autorefresh").checked) return;
  try {
    await poll();
    await render();
    $("err").textContent = "";
  } catch (e) {
    $("err").textContent = " · " + e;
  }
}

// ------------------------------------------------------------- events

window.addEventListener("hashchange", () => { detail = null; render(); });

document.addEventListener("input", (e) => {
  if (e.target.id === "search") { searchTerm = e.target.value; render(); }
});

document.addEventListener("submit", async (e) => {
  if (e.target.id !== "jobform") return;
  e.preventDefault();
  const entrypoint = $("entrypoint").value.trim();
  if (!entrypoint) return;
  try {
    await j("/api/jobs/", {method: "POST",
      headers: {"content-type": "application/json"},
      body: JSON.stringify({entrypoint})});
    await tick(true);
  } catch (err) { $("err").textContent = " · " + err; }
});

document.addEventListener("click", async (e) => {
  try {
    const drill = e.target.closest(".drill");
    if (drill) {
      const d = await j(`/api/${drill.dataset.kind}/${drill.dataset.id}`);
      detail = {title: `${drill.dataset.kind.slice(0, -1)} ` +
                `${drill.dataset.id.slice(0, 12)}`,
                body: JSON.stringify(d, null, 2)};
      render();
      return;
    }
    const logs = e.target.closest(".logs[data-id]");
    if (logs) {
      const body = await j(`/api/jobs/${logs.dataset.id}/logs`);
      detail = {title: `job ${logs.dataset.id} logs (tail)`,
                body: String(body.logs || "").split("\n").slice(-300)
                  .join("\n")};
      render();
      return;
    }
    const logfile = e.target.closest(".logfile");
    if (logfile) {
      logSel = {node: logfile.dataset.node, file: logfile.dataset.file};
      await render();
      return;
    }
    const stop = e.target.closest(".stopjob");
    if (stop) {
      await fetch(`/api/jobs/${stop.dataset.id}/stop`, {method: "POST"});
      await tick(true);
      return;
    }
    if (e.target.id === "closedetail") { detail = null; render(); }
  } catch (err) {
    $("err").textContent = " · " + err;  // e.g. drilling a just-GC'd actor
  }
});

const POLL_MS = 2000;
tick(true);
setInterval(tick, POLL_MS);
