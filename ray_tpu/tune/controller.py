"""TuneController: actor-based trial lifecycle.

Reference analog: python/ray/tune/execution/tune_controller.py:68 (trial
actors over the actor manager; scheduler decisions drive stop/exploit).
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune import session as tune_session
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, STOP, FIFOScheduler

logger = logging.getLogger(__name__)

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERRORED = "ERRORED"


class TrialRunner:
    """Actor hosting one trial's function trainable."""

    def __init__(self, trial_id: str, storage_path: str):
        self.trial_id = trial_id
        self.storage_path = storage_path
        self.session = None
        self.thread = None

    def start(self, fn_payload: bytes, config: Dict,
              checkpoint_dir: Optional[str]) -> bool:
        import cloudpickle

        fn = cloudpickle.loads(fn_payload)
        self.session = tune_session.init_session(
            trial_id=self.trial_id, config=config,
            storage_path=self.storage_path, checkpoint_dir=checkpoint_dir)

        def run():
            try:
                fn(config)
            except BaseException as e:  # noqa: BLE001
                self.session.error = e
                self.session.results.put(
                    {"error": traceback.format_exc(), "trial_id": self.trial_id})
            finally:
                self.session.finished.set()

        self.thread = threading.Thread(target=run, daemon=True,
                                       name=f"tune-trial-{self.trial_id}")
        self.thread.start()
        return True

    def poll(self, max_results: int = 32) -> Dict[str, Any]:
        out = []
        if self.session is not None:
            while len(out) < max_results and not self.session.results.empty():
                out.append(self.session.results.get_nowait())
        return {"results": out,
                "finished": self.session is not None and self.session.finished.is_set()}


class Trial:
    def __init__(self, trial_id: str, config: Dict):
        self.trial_id = trial_id
        self.config = config
        self.status = PENDING
        self.actor = None
        self.last_result: Dict = {}
        self.history: List[Dict] = []
        self.checkpoint_dir: Optional[str] = None
        self.error: Optional[str] = None
        self.restarts = 0


class TuneController:
    def __init__(self, trainable: Callable, variants: List[Dict], *,
                 scheduler=None, storage_path: str, run_name: str,
                 max_concurrent: int = 4,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 restored_trials: Optional[List[Trial]] = None,
                 snapshot_interval_s: float = 5.0,
                 searcher=None, num_samples: int = 0):
        self.trainable = trainable
        self.scheduler = scheduler or FIFOScheduler()
        self.storage_path = os.path.join(storage_path, run_name)
        os.makedirs(self.storage_path, exist_ok=True)
        self.max_concurrent = max_concurrent
        self.resources = resources_per_trial or {"CPU": 0}
        # A searcher suggests configs sequentially (conditioning on prior
        # completions); without one the variant list is pre-expanded.
        self.searcher = searcher
        self.num_samples = num_samples
        if restored_trials is not None:
            self.trials = restored_trials
        elif searcher is not None:
            self.trials = []
        else:
            self.trials = [Trial(f"trial_{i:04d}", cfg)
                           for i, cfg in enumerate(variants)]
        self.snapshot_interval_s = snapshot_interval_s
        self._last_snapshot = 0.0

    def _maybe_suggest(self):
        """Top up PENDING trials from the searcher while capacity and the
        sample budget allow."""
        if self.searcher is None:
            return
        active = [t for t in self.trials if t.status in (PENDING, RUNNING)]
        while (len(self.trials) < self.num_samples
               and len(active) < self.max_concurrent):
            tid = f"trial_{len(self.trials):04d}"
            cfg = self.searcher.suggest(tid)
            if cfg is None:
                return
            trial = Trial(tid, cfg)
            self.trials.append(trial)
            active.append(trial)

    def _snapshot(self, force: bool = False):
        from ray_tpu.tune import experiment_state

        now = time.monotonic()
        if not force and now - self._last_snapshot < self.snapshot_interval_s:
            return
        self._last_snapshot = now
        try:
            experiment_state.save_snapshot(
                self.storage_path, self.trials,
                {"max_concurrent": self.max_concurrent,
                 "resources": self.resources})
        except Exception:
            logger.exception("experiment snapshot failed")

    def run(self, poll_interval: float = 0.1) -> List[Trial]:
        import cloudpickle

        from ray_tpu.tune import experiment_state

        payload = cloudpickle.dumps(self.trainable)
        try:
            experiment_state.save_trainable(self.storage_path, self.trainable)
        except Exception:
            logger.exception("could not persist trainable")
        RunnerActor = ray_tpu.remote(TrialRunner)

        def start_trial(trial: Trial, checkpoint_dir=None, config=None):
            if checkpoint_dir is None and trial.checkpoint_dir:
                # Restored mid-flight trial: resume from its last persisted
                # checkpoint rather than from scratch.
                checkpoint_dir = trial.checkpoint_dir
            trial.actor = RunnerActor.options(
                num_cpus=self.resources.get("CPU", 0),
                num_tpus=self.resources.get("TPU", 0)).remote(
                trial.trial_id, self.storage_path)
            cfg = config if config is not None else trial.config
            trial.config = cfg
            if hasattr(self.scheduler, "on_trial_config"):
                # Config-aware schedulers (PB2's GP conditions on the
                # hyperparameters each trial is running).
                self.scheduler.on_trial_config(trial.trial_id, cfg)
            ray_tpu.get(trial.actor.start.remote(payload, cfg, checkpoint_dir),
                        timeout=120)
            trial.status = RUNNING

        while True:
            self._maybe_suggest()
            running = [t for t in self.trials if t.status == RUNNING]
            pending = [t for t in self.trials if t.status == PENDING]
            for trial in pending[:max(0, self.max_concurrent - len(running))]:
                start_trial(trial)
            running = [t for t in self.trials if t.status == RUNNING]
            if not running and not pending:
                break
            polls = ray_tpu.get([t.actor.poll.remote() for t in running],
                                timeout=120)
            for trial, poll in zip(running, polls):
                decision = CONTINUE
                for item in poll["results"]:
                    if "error" in item:
                        trial.status = ERRORED
                        trial.error = item["error"]
                        break
                    metrics = item["metrics"]
                    trial.last_result = metrics
                    trial.history.append(metrics)
                    if item.get("checkpoint_dir"):
                        trial.checkpoint_dir = self._persist_checkpoint(
                            trial, item["checkpoint_dir"])
                    decision = self.scheduler.on_result(trial.trial_id, metrics)
                    if decision != CONTINUE:
                        break
                if trial.status == ERRORED:
                    self._kill(trial)
                elif decision == STOP:
                    trial.status = TERMINATED
                    self._kill(trial)
                elif decision == EXPLOIT:
                    self._exploit(trial, start_trial)
                elif poll["finished"]:
                    trial.status = TERMINATED
                    self._kill(trial)
                if trial.status in (TERMINATED, ERRORED):
                    # Cohort-tracking schedulers (HyperBand) must stop
                    # waiting on this trial's rung results.
                    try:
                        self.scheduler.on_trial_complete(trial.trial_id)
                    except Exception:
                        logger.exception("scheduler on_trial_complete failed")
                if (self.searcher is not None
                        and trial.status in (TERMINATED, ERRORED)):
                    try:
                        # Errored trials report None: a crashing config must
                        # not enter the searcher's observations as a success.
                        self.searcher.on_trial_complete(
                            trial.trial_id,
                            None if trial.status == ERRORED
                            else trial.last_result)
                    except Exception:
                        logger.exception("searcher completion hook failed")
            self._snapshot()
            time.sleep(poll_interval)
        self._snapshot(force=True)
        return self.trials

    def _persist_checkpoint(self, trial: Trial, src_dir: str) -> str:
        dest = os.path.join(self.storage_path, trial.trial_id,
                            f"checkpoint_{len(trial.history):06d}")
        if os.path.abspath(src_dir) != dest and os.path.exists(src_dir):
            shutil.copytree(src_dir, dest, dirs_exist_ok=True)
        return dest

    def _exploit(self, trial: Trial, start_trial):
        """PBT exploit/explore: restart from a better trial's checkpoint with
        mutated config."""
        target_id = self.scheduler.exploit_target(trial.trial_id)
        target = next((t for t in self.trials if t.trial_id == target_id), None)
        if target is None or target.checkpoint_dir is None:
            return  # nothing to exploit yet
        new_config = self.scheduler.explore(dict(target.config)) \
            if hasattr(self.scheduler, "explore") else dict(target.config)
        logger.info("PBT: %s exploits %s (new config %s)", trial.trial_id,
                    target.trial_id, new_config)
        self._kill(trial)
        trial.restarts += 1
        start_trial(trial, checkpoint_dir=target.checkpoint_dir,
                    config=new_config)

    @staticmethod
    def _kill(trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
