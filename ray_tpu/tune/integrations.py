"""External searcher integrations: Optuna / HyperOpt adapters.

Reference analog: python/ray/tune/search/optuna/optuna_search.py:1 and
search/hyperopt/hyperopt_search.py — thin adapters translating between the
external library's ask/tell interface and tune's Searcher protocol
(suggest/on_trial_complete). Both libraries are OPTIONAL: the adapters
import lazily and raise a clear error naming the native fallback
(TPESearcher covers the hyperopt/optuna-TPE role without the dependency).

Search-space translation: tune Domains map onto the library's native
distributions (uniform/loguniform/randint/choice), so library-side
samplers see the true space, not a flattened one.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from ray_tpu.tune.search import (Categorical, Domain, GridSearch,
                                 LogUniform, RandInt, Searcher, Uniform)

logger = logging.getLogger(__name__)


def _missing(lib: str, pipname: str):
    return ImportError(
        f"{lib} is not installed; `pip install {pipname}` to use this "
        "searcher, or use the dependency-free native TPESearcher "
        "(ray_tpu.tune.search.TPESearcher) which covers the TPE role")


class OptunaSearch(Searcher):
    """Optuna ask/tell adapter (reference: OptunaSearch).

    Each tune trial is one optuna trial: suggest() calls study.ask() and
    samples the translated space; on_trial_complete() tells the result.
    """

    def __init__(self, param_space: Dict, metric: str, mode: str = "max",
                 *, sampler=None, seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as e:
            raise _missing("optuna", "optuna") from e
        assert mode in ("max", "min")
        self._optuna = optuna
        self.space = param_space
        self.metric = metric
        optuna.logging.set_verbosity(optuna.logging.WARNING)
        self.study = optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=sampler or optuna.samplers.TPESampler(seed=seed))
        self._trials: Dict[str, object] = {}

    def _sample(self, trial, name: str, dom):
        if isinstance(dom, GridSearch):
            return trial.suggest_categorical(name, list(dom.values))
        if isinstance(dom, LogUniform):
            return trial.suggest_float(name, dom.low, dom.high, log=True)
        if isinstance(dom, Uniform):
            return trial.suggest_float(name, dom.low, dom.high)
        if isinstance(dom, RandInt):
            return trial.suggest_int(name, dom.low, dom.high - 1)
        if isinstance(dom, Categorical):
            return trial.suggest_categorical(name, list(dom.categories))
        if isinstance(dom, Domain):
            raise ValueError(f"unsupported domain {type(dom).__name__}")
        return dom  # constant

    def suggest(self, trial_id: str) -> Dict:
        trial = self.study.ask()
        self._trials[trial_id] = trial
        return {k: self._sample(trial, k, v) for k, v in self.space.items()}

    def on_trial_complete(self, trial_id: str, result: Optional[Dict]):
        trial = self._trials.pop(trial_id, None)
        if trial is None:
            return
        value = (result or {}).get(self.metric)
        state = self._optuna.trial.TrialState.COMPLETE
        if value is None:
            state = self._optuna.trial.TrialState.FAIL
        self.study.tell(trial, value, state=state)


class HyperOptSearch(Searcher):
    """hyperopt TPE adapter (reference: HyperOptSearch)."""

    def __init__(self, param_space: Dict, metric: str, mode: str = "max",
                 *, seed: Optional[int] = None):
        try:
            import hyperopt
            from hyperopt import hp
        except ImportError as e:
            raise _missing("hyperopt", "hyperopt") from e
        assert mode in ("max", "min")
        import numpy as np

        self._hpo = hyperopt
        self.metric = metric
        self.mode = mode
        self.space = {}
        for k, dom in param_space.items():
            if isinstance(dom, GridSearch):
                self.space[k] = hp.choice(k, list(dom.values))
            elif isinstance(dom, LogUniform):
                self.space[k] = hp.loguniform(
                    k, np.log(dom.low), np.log(dom.high))
            elif isinstance(dom, Uniform):
                self.space[k] = hp.uniform(k, dom.low, dom.high)
            elif isinstance(dom, RandInt):
                self.space[k] = hp.randint(k, dom.low, dom.high)
            elif isinstance(dom, Categorical):
                self.space[k] = hp.choice(k, list(dom.categories))
            elif isinstance(dom, Domain):
                raise ValueError(f"unsupported domain {type(dom).__name__}")
            else:
                self.space[k] = dom
        self.trials = hyperopt.Trials()
        self.domain = hyperopt.Domain(lambda c: 0.0, self.space)
        self.rng = np.random.default_rng(seed)
        self._tids: Dict[str, int] = {}
        self._next_tid = 0

    def suggest(self, trial_id: str) -> Dict:
        import numpy as np

        tid = self._next_tid
        self._next_tid += 1
        seed = int(self.rng.integers(2 ** 31 - 1))
        new = self._hpo.tpe.suggest(
            [tid], self.domain, self.trials, seed)
        self.trials.insert_trial_docs(new)
        self.trials.refresh()
        self._tids[trial_id] = tid
        vals = {k: v[0] for k, v in new[0]["misc"]["vals"].items() if v}
        cfg = self._hpo.space_eval(self.space, vals)
        return dict(cfg)

    def on_trial_complete(self, trial_id: str, result: Optional[Dict]):
        tid = self._tids.pop(trial_id, None)
        if tid is None:
            return
        value = (result or {}).get(self.metric)
        for doc in self.trials.trials:
            if doc["tid"] != tid:
                continue
            if value is None:
                doc["state"] = self._hpo.JOB_STATE_ERROR
            else:
                loss = -value if self.mode == "max" else value
                doc["result"] = {"loss": loss, "status": self._hpo.STATUS_OK}
                doc["state"] = self._hpo.JOB_STATE_DONE
        self.trials.refresh()


class AxSearch(Searcher):
    """Ax (Adaptive Experimentation) adapter (reference:
    tune/search/ax/ax_search.py). Bayesian optimization through
    AxClient's attach/complete trial interface; the translated space
    keeps true ranges + log scaling."""

    def __init__(self, param_space: Dict, metric: str, mode: str = "max",
                 *, seed: Optional[int] = None):
        try:
            from ax.service.ax_client import AxClient
            from ax.service.utils.instantiation import ObjectiveProperties
        except ImportError as e:
            raise _missing("ax-platform", "ax-platform") from e
        assert mode in ("max", "min")
        self.metric = metric
        self.space = param_space
        params = []
        for name, dom in param_space.items():
            if isinstance(dom, (Uniform, LogUniform)):
                params.append({"name": name, "type": "range",
                               "bounds": [dom.low, dom.high],
                               "log_scale": isinstance(dom, LogUniform)})
            elif isinstance(dom, RandInt):
                params.append({"name": name, "type": "range",
                               "bounds": [dom.low, dom.high - 1],
                               "value_type": "int"})
            elif isinstance(dom, (Categorical, GridSearch)):
                values = (dom.categories if isinstance(dom, Categorical)
                          else dom.values)
                params.append({"name": name, "type": "choice",
                               "values": list(values)})
            elif isinstance(dom, Domain):
                raise ValueError(
                    f"unsupported domain {type(dom).__name__}")
            else:
                params.append({"name": name, "type": "fixed",
                               "value": dom})
        self.client = AxClient(random_seed=seed, verbose_logging=False)
        self.client.create_experiment(
            name="ray_tpu_tune", parameters=params,
            objectives={metric: ObjectiveProperties(
                minimize=mode == "min")})
        self._trials: Dict[str, int] = {}

    def suggest(self, trial_id: str) -> Dict:
        cfg, ax_idx = self.client.get_next_trial()
        self._trials[trial_id] = ax_idx
        return dict(cfg)

    def on_trial_complete(self, trial_id: str, result: Optional[Dict]):
        ax_idx = self._trials.pop(trial_id, None)
        if ax_idx is None:
            return
        value = (result or {}).get(self.metric)
        if value is None:
            self.client.log_trial_failure(ax_idx)
        else:
            self.client.complete_trial(
                ax_idx, raw_data={self.metric: float(value)})


class HEBOSearch(Searcher):
    """HEBO adapter (reference: tune/search/hebo/hebo_search.py).
    Heteroscedastic-BO through HEBO's suggest/observe dataframe
    interface."""

    def __init__(self, param_space: Dict, metric: str, mode: str = "max",
                 *, seed: Optional[int] = None):
        try:
            from hebo.design_space.design_space import DesignSpace
            from hebo.optimizers.hebo import HEBO
        except ImportError as e:
            raise _missing("HEBO", "HEBO") from e
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self._constants: Dict[str, object] = {}
        specs = []
        for name, dom in param_space.items():
            if isinstance(dom, LogUniform):
                specs.append({"name": name, "type": "pow",
                              "lb": dom.low, "ub": dom.high})
            elif isinstance(dom, Uniform):
                specs.append({"name": name, "type": "num",
                              "lb": dom.low, "ub": dom.high})
            elif isinstance(dom, RandInt):
                specs.append({"name": name, "type": "int",
                              "lb": dom.low, "ub": dom.high - 1})
            elif isinstance(dom, (Categorical, GridSearch)):
                values = (dom.categories if isinstance(dom, Categorical)
                          else dom.values)
                specs.append({"name": name, "type": "cat",
                              "categories": list(values)})
            elif isinstance(dom, Domain):
                raise ValueError(
                    f"unsupported domain {type(dom).__name__}")
            else:
                # Constants pass through to every config (like the other
                # adapters), not into HEBO's design space.
                self._constants[name] = dom
        self.opt = HEBO(DesignSpace().parse_specs(specs),
                        rand_sample=4, scramble_seed=seed)
        self._pending: Dict[str, object] = {}

    def suggest(self, trial_id: str) -> Dict:
        rec = self.opt.suggest(n_suggestions=1)
        self._pending[trial_id] = rec
        cfg = {k: rec[k].iloc[0] for k in rec.columns}
        cfg.update(self._constants)
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[Dict]):
        import numpy as np

        rec = self._pending.pop(trial_id, None)
        if rec is None:
            return
        value = (result or {}).get(self.metric)
        if value is None:
            return  # HEBO has no failure notion; drop the observation
        y = -float(value) if self.mode == "max" else float(value)
        self.opt.observe(rec, np.array([[y]]))
