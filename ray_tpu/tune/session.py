"""Per-trial session for function trainables (tune.report)."""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

_session: Optional["TuneSession"] = None


class TuneSession:
    def __init__(self, trial_id: str, config: Dict, storage_path: str,
                 checkpoint_dir: Optional[str]):
        self.trial_id = trial_id
        self.config = config
        self.storage_path = storage_path
        self.checkpoint_dir = checkpoint_dir
        self.results: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self.iteration = 0


def init_session(**kwargs) -> TuneSession:
    global _session
    _session = TuneSession(**kwargs)
    return _session


def get_session() -> TuneSession:
    if _session is None:
        raise RuntimeError("not inside a tune trial")
    return _session


def report(metrics: Dict[str, Any], checkpoint_dir: Optional[str] = None):
    s = get_session()
    s.iteration += 1
    m = dict(metrics)
    m.setdefault("training_iteration", s.iteration)
    s.results.put({"metrics": m, "checkpoint_dir": checkpoint_dir,
                   "trial_id": s.trial_id})


def get_checkpoint_dir() -> Optional[str]:
    return get_session().checkpoint_dir


def get_trial_id() -> str:
    return get_session().trial_id
