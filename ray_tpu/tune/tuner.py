"""Tuner: the public tuning API.

Reference analog: python/ray/tune/tuner.py:312 Tuner.fit -> ResultGrid.
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.config import RunConfig
from ray_tpu.tune.controller import ERRORED, TERMINATED, Trial, TuneController
from ray_tpu.tune.search import generate_variants


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    search_alg: Any = None   # a tune.search.Searcher (e.g. TPESearcher)
    seed: int = 0


class TrialResult:
    def __init__(self, trial: Trial):
        self.trial_id = trial.trial_id
        self.config = trial.config
        self.metrics = trial.last_result
        self.metrics_history = trial.history
        self.checkpoint_dir = trial.checkpoint_dir
        self.error = trial.error


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str], mode: str):
        self._results = [TrialResult(t) for t in trials]
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> TrialResult:
        return self._results[i]

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        assert metric, "metric required to rank results"
        scored = [r for r in self._results if metric in (r.metrics or {})]
        if not scored:
            raise ValueError("no trial reported the metric " + metric)
        return sorted(scored, key=lambda r: r.metrics[metric],
                      reverse=(mode == "max"))[0]

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([
            {"trial_id": r.trial_id, **(r.metrics or {}),
             **{f"config/{k}": v for k, v in r.config.items()}}
            for r in self._results])


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self.resources_per_trial = resources_per_trial

        self._restored_trials = None

    def fit(self) -> ResultGrid:
        # In searcher mode the controller suggests configs sequentially and
        # ignores pre-expanded variants — don't materialize them.
        variants = [] if self.tune_config.search_alg is not None else \
            generate_variants(self.param_space,
                              self.tune_config.num_samples,
                              self.tune_config.seed)
        run_name = self.run_config.name or f"tune-{uuid.uuid4().hex[:8]}"
        controller = TuneController(
            self.trainable, variants,
            scheduler=self.tune_config.scheduler,
            storage_path=self.run_config.storage_path or "/tmp/ray_tpu_results",
            run_name=run_name,
            max_concurrent=self.tune_config.max_concurrent_trials,
            resources_per_trial=self.resources_per_trial,
            restored_trials=self._restored_trials,
            searcher=self.tune_config.search_alg,
            num_samples=self.tune_config.num_samples)
        trials = controller.run()
        return ResultGrid(trials, self.tune_config.metric, self.tune_config.mode)

    @classmethod
    def restore(cls, path: str, *, trainable: Callable = None,
                tune_config: Optional[TuneConfig] = None,
                resources_per_trial: Optional[Dict[str, float]] = None
                ) -> "Tuner":
        """Rebuild a Tuner from a run dir written by a previous fit().

        Reference analog: Tuner.restore (tuner.py) + experiment-state
        snapshots. `path` is the run dir (storage_path/run_name).
        Finished trials keep their results; interrupted (RUNNING) and
        PENDING trials are re-queued — RUNNING ones resume from their last
        persisted checkpoint when one exists."""
        import os

        from ray_tpu.tune import experiment_state
        from ray_tpu.tune.controller import (ERRORED, PENDING, RUNNING,
                                             TERMINATED)

        state = experiment_state.load_snapshot(path)
        if state is None:
            raise FileNotFoundError(f"no experiment snapshot under {path}")
        if trainable is None:
            trainable = experiment_state.load_trainable(path)
        storage_path, run_name = os.path.split(path.rstrip("/"))
        settings = state.get("settings", {})
        if resources_per_trial is None:
            resources_per_trial = settings.get("resources")
        if tune_config is None:
            tune_config = TuneConfig()
            if settings.get("max_concurrent"):
                tune_config = dataclasses.replace(
                    tune_config,
                    max_concurrent_trials=settings["max_concurrent"])
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=RunConfig(name=run_name,
                                         storage_path=storage_path),
                    resources_per_trial=resources_per_trial)
        trials = []
        for rec in state["trials"]:
            t = Trial(rec["trial_id"], rec["config"])
            t.last_result = rec["last_result"]
            t.history = rec["history"]
            t.checkpoint_dir = rec["checkpoint_dir"]
            t.error = rec["error"]
            t.restarts = rec["restarts"]
            t.status = rec["status"]
            if t.status in (RUNNING, PENDING):
                t.status = PENDING      # re-queue; resumes from checkpoint
            trials.append(t)
        tuner._restored_trials = trials
        return tuner
