"""Trial schedulers: FIFO, ASHA, PBT.

Reference analog: python/ray/tune/schedulers/ (async_hyperband.py
ASHAScheduler, pbt.py:221 PopulationBasedTraining).
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Tuple

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"   # PBT: replace weights+config from a better trial


class TrialScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        """Trial finished, errored, or was stopped: schedulers tracking
        cohorts (HyperBand) must not wait on it any longer."""

    def exploit_target(self, trial_id: str):
        return None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving: at each rung, trials outside the top
    1/reduction_factor of completed rung results are stopped."""

    def __init__(self, metric: str, mode: str = "max", *,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung milestone -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for milestone in self.milestones:
            if t == milestone:
                recorded = self.rungs.setdefault(milestone, [])
                recorded.append(float(value))
                if len(recorded) >= self.rf:
                    ranked = sorted(recorded, reverse=(self.mode == "max"))
                    cutoff = ranked[max(0, len(ranked) // self.rf - 1)]
                    bad = value < cutoff if self.mode == "max" else value > cutoff
                    if bad:
                        return STOP
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: schedulers/hyperband.py): trials
    are assigned round-robin to brackets with different (budget, halving)
    trade-offs; within a bracket, successive halving keeps the top
    1/reduction_factor at each rung. Unlike ASHA, halving decisions wait
    for the whole rung cohort, so no trial is stopped on a partial view."""

    def __init__(self, metric: str, mode: str = "max", *,
                 max_t: int = 81, reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        self.time_attr = time_attr
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        # Bracket i starts trials at budget max_t * rf^-(s_max - i).
        self.brackets: List[Dict] = []
        for s in range(s_max, -1, -1):
            r0 = max(1, int(max_t * self.rf ** (-s)))
            milestones = []
            t = r0
            while t < max_t:
                milestones.append(t)
                t *= self.rf
            self.brackets.append({"milestones": milestones,
                                  "rungs": {}, "trials": set()})
        self._assign: Dict[str, int] = {}
        self._next_bracket = 0
        self._decided: Dict[tuple, str] = {}

    def _bracket_of(self, trial_id: str) -> Dict:
        if trial_id not in self._assign:
            self._assign[trial_id] = self._next_bracket
            self.brackets[self._next_bracket]["trials"].add(trial_id)
            self._next_bracket = (self._next_bracket + 1) % len(self.brackets)
        return self.brackets[self._assign[trial_id]]

    def on_trial_complete(self, trial_id: str) -> None:
        b = self._assign.get(trial_id)
        if b is None:
            return
        bracket = self.brackets[b]
        bracket["trials"].discard(trial_id)
        # A shrunken cohort may now be complete at some rung: re-evaluate so
        # the survivors' deferred decisions exist for their next report.
        for milestone in bracket["milestones"]:
            rung = bracket["rungs"].get(milestone)
            if rung:
                rung.pop(trial_id, None)
                self._maybe_halve(b, milestone)

    def _maybe_halve(self, bracket_idx: int, milestone: int) -> None:
        bracket = self.brackets[bracket_idx]
        rung = bracket["rungs"].get(milestone, {})
        cohort = bracket["trials"]
        waiting = [tid for tid in cohort if tid not in rung]
        if not rung or waiting:
            return  # synchronous: wait for every live trial in the cohort
        keep = max(1, len(rung) // self.rf)
        ranked = sorted(rung, key=rung.get, reverse=(self.mode == "max"))
        survivors = set(ranked[:keep])
        for tid in list(rung):
            decision = CONTINUE if tid in survivors else STOP
            self._decided[(bracket_idx, milestone, tid)] = decision
            if decision == STOP:
                cohort.discard(tid)

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        bracket = self._bracket_of(trial_id)
        b = self._assign[trial_id]
        # A halving decided after this trial passed the rung (it reported
        # early, or the cohort completed via on_trial_complete) is delivered
        # at its NEXT report.
        for milestone in bracket["milestones"]:
            if milestone <= t and self._decided.get(
                    (b, milestone, trial_id)) == STOP:
                return STOP
        for milestone in bracket["milestones"]:
            if t == milestone:
                rung = bracket["rungs"].setdefault(milestone, {})
                rung[trial_id] = float(value)
                self._maybe_halve(b, milestone)
                decision = self._decided.get((b, milestone, trial_id))
                if decision is not None:
                    return decision
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average metric falls below the median of
    the running averages of all trials at the same step (after a grace
    period). Reference analog: tune/schedulers/median_stopping_rule.py."""

    def __init__(self, metric: str, mode: str = "max", *,
                 grace_period: int = 4, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        # trial_id -> (sum, count) of the metric so far
        self._means: Dict[str, List[float]] = {}

    def _running_avg(self, trial_id: str) -> Optional[float]:
        s = self._means.get(trial_id)
        return None if not s or s[1] == 0 else s[0] / s[1]

    def on_result(self, trial_id: str, result: Dict) -> str:
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        acc = self._means.setdefault(trial_id, [0.0, 0])
        acc[0] += float(value)
        acc[1] += 1
        t = result.get(self.time_attr, acc[1])
        if t < self.grace or len(self._means) < self.min_samples:
            return CONTINUE
        others = [self._running_avg(tid) for tid in self._means
                  if tid != trial_id]
        others = [v for v in others if v is not None]
        if len(others) < self.min_samples - 1:
            return CONTINUE
        ranked = sorted(others)
        mid = len(ranked) // 2
        median = (ranked[mid] if len(ranked) % 2
                  else 0.5 * (ranked[mid - 1] + ranked[mid]))
        mine = self._running_avg(trial_id)
        worse = mine < median if self.mode == "max" else mine > median
        return STOP if worse else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: every `perturbation_interval` iterations, bottom-quantile trials
    exploit (copy checkpoint+config of) a top-quantile trial and explore
    (mutate hyperparameters)."""

    def __init__(self, metric: str, mode: str = "max", *,
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25, seed: int = 0,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self.latest: Dict[str, Dict] = {}  # trial_id -> last result

    def on_result(self, trial_id: str, result: Dict) -> str:
        self.latest[trial_id] = result
        t = result.get(self.time_attr, 0)
        if t == 0 or t % self.interval != 0 or len(self.latest) < 2:
            return CONTINUE
        scores = {tid: r.get(self.metric) for tid, r in self.latest.items()
                  if r.get(self.metric) is not None}
        if trial_id not in scores or len(scores) < 2:
            return CONTINUE
        ranked = sorted(scores, key=lambda tid: scores[tid],
                        reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) * self.quantile))
        if trial_id in ranked[-k:] and trial_id not in ranked[:k]:
            return EXPLOIT
        return CONTINUE

    def exploit_target(self, trial_id: str) -> Optional[str]:
        scores = {tid: r.get(self.metric) for tid, r in self.latest.items()
                  if r.get(self.metric) is not None and tid != trial_id}
        if not scores:
            return None
        ranked = sorted(scores, key=lambda tid: scores[tid],
                        reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) * self.quantile))
        return self.rng.choice(ranked[:k])

    def explore(self, config: Dict) -> Dict:
        """Mutate hyperparameters (x0.8 / x1.25 or resample)."""
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if isinstance(spec, Domain):
                out[key] = spec.sample(self.rng)
            elif isinstance(spec, list):
                out[key] = self.rng.choice(spec)
            elif callable(spec):
                out[key] = spec()
            elif isinstance(out[key], (int, float)):
                factor = self.rng.choice([0.8, 1.25])
                out[key] = type(out[key])(out[key] * factor)
        return out


class PB2(PopulationBasedTraining):
    """Population Based Bandits: PBT whose explore() selects new
    hyperparameters by GP-UCB over observed (time, config) -> reward-delta
    data instead of random perturbation.

    Reference analog: python/ray/tune/schedulers/pb2.py (GPy-backed); this
    is a dependency-free numpy GP (RBF kernel, fixed hyperparameters on
    standardized data) — the PB2 selection rule without the GPy stack.

    hyperparam_bounds: {key: (low, high)} continuous bounds; keys listed in
    log_scale_keys are modeled in log10 space (learning rates).
    """

    def __init__(self, metric: str, mode: str = "max", *,
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Dict[str, Tuple[float, float]],
                 quantile_fraction: float = 0.25, seed: int = 0,
                 log_scale_keys: Tuple[str, ...] = (),
                 time_attr: str = "training_iteration"):
        super().__init__(metric, mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed,
                         time_attr=time_attr)
        if not hyperparam_bounds:
            raise ValueError("PB2 needs hyperparam_bounds")
        self.bounds = dict(hyperparam_bounds)
        self.log_keys = set(log_scale_keys)
        self.keys = sorted(self.bounds)
        self.configs: Dict[str, Dict] = {}       # trial_id -> live config
        self._prev: Dict[str, Tuple[float, float]] = {}  # tid -> (t, score)
        self.data: list = []                     # rows: [t, *x, delta]

    # Controller hook: runs at every (re)start, including exploit restarts.
    def on_trial_config(self, trial_id: str, config: Dict) -> None:
        self.configs[trial_id] = dict(config)
        # Drop the pre-restart (t, score) anchor: an exploit copies a better
        # trial's weights, and crediting that score jump to the NEW config
        # would feed the GP a huge spurious delta.
        self._prev.pop(trial_id, None)

    def _x_of(self, config: Dict) -> list:
        out = []
        for k in self.keys:
            v = float(config.get(k, self.bounds[k][0]))
            out.append(math.log10(max(v, 1e-12)) if k in self.log_keys
                       else v)
        return out

    def _norm_bounds(self) -> list:
        out = []
        for k in self.keys:
            lo, hi = self.bounds[k]
            if k in self.log_keys:
                lo, hi = math.log10(max(lo, 1e-12)), math.log10(max(hi, 1e-12))
            out.append((float(lo), float(hi)))
        return out

    def on_result(self, trial_id: str, result: Dict) -> str:
        score = result.get(self.metric)
        t = float(result.get(self.time_attr, 0))
        if score is not None:
            prev = self._prev.get(trial_id)
            cfg = self.configs.get(trial_id)
            if prev is not None and cfg is not None and t > prev[0]:
                delta = (score - prev[1]) / (t - prev[0])
                if self.mode == "min":
                    delta = -delta
                self.data.append([t] + self._x_of(cfg) + [delta])
                if len(self.data) > 512:
                    self.data = self.data[-512:]
            self._prev[trial_id] = (t, float(score))
        return super().on_result(trial_id, result)

    # -- GP-UCB selection --------------------------------------------------
    def _gp_ucb_choice(self, t_now: float):
        import numpy as np

        nb = self._norm_bounds()
        d = len(self.keys)
        # Candidate set: random in bounds at the current time.
        n_cand = 256
        cand = np.empty((n_cand, d))
        for j, (lo, hi) in enumerate(nb):
            cand[:, j] = np.asarray(
                [self.rng.uniform(lo, hi) for _ in range(n_cand)])
        if len(self.data) < 4:
            return cand[0]
        arr = np.asarray(self.data, dtype=np.float64)
        Xr, y = arr[:, :-1], arr[:, -1]
        # Normalize inputs to [0,1] (time by its own range), standardize y.
        t_lo, t_hi = Xr[:, 0].min(), max(Xr[:, 0].max(), t_now)
        scale = [(t_lo, max(t_hi - t_lo, 1e-9))] + [
            (lo, max(hi - lo, 1e-9)) for lo, hi in nb]
        X = (Xr - np.asarray([s[0] for s in scale])) / np.asarray(
            [s[1] for s in scale])
        y_mu, y_sd = y.mean(), max(y.std(), 1e-9)
        ys = (y - y_mu) / y_sd
        Xc = np.hstack([np.full((n_cand, 1), t_now), cand])
        Xc = (Xc - np.asarray([s[0] for s in scale])) / np.asarray(
            [s[1] for s in scale])
        # RBF GP with fixed hyperparameters on standardized data.
        ell, sf2, sn2 = 0.3, 1.0, 0.01
        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return sf2 * np.exp(-d2 / (2 * ell * ell))
        K = k(X, X) + sn2 * np.eye(len(X))
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return cand[0]
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, ys))
        Ks = k(Xc, X)
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(sf2 - (v * v).sum(0), 1e-12, None)
        beta = 2.0 * np.log(max(len(self.data), 2) * n_cand)
        ucb = mu + np.sqrt(beta * var)
        return cand[int(np.argmax(ucb))]

    def explore(self, config: Dict) -> Dict:
        t_now = max((t for t, _ in self._prev.values()), default=0.0)
        x = self._gp_ucb_choice(t_now)
        out = dict(config)
        for j, key in enumerate(self.keys):
            v = float(x[j])
            if key in self.log_keys:
                v = 10.0 ** v
            lo, hi = self.bounds[key]
            v = min(max(v, lo), hi)
            if isinstance(config.get(key), int):
                v = int(round(v))
            out[key] = v
        return out
