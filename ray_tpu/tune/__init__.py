from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    HyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (  # noqa: F401
    BOHBSearcher,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.session import (  # noqa: F401
    get_checkpoint_dir,
    get_trial_id,
    report,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner  # noqa: F401
