"""Search spaces and trial generation.

Reference analog: python/ray/tune/search/ (sample.py domains,
basic_variant.py BasicVariantGenerator). Grid axes expand combinatorially;
stochastic domains sample `num_samples` times.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high
        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        # Clamp: exp(log(x)) can land an ulp outside [low, high].
        return min(max(math.exp(rng.uniform(self.log_low, self.log_high)),
                       self.low), self.high)


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


class Searcher:
    """Sequential config suggestion (reference: tune/search/searcher.py —
    the interface Optuna/HyperOpt integrations implement). The controller
    asks `suggest` when a trial slot frees and reports back completions, so
    later suggestions condition on earlier results."""

    def suggest(self, trial_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict]):
        pass


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator over a Domain dict
    (the role hyperopt plays in the reference, without the dependency).

    Numeric params: candidates drawn from a KDE over the good quantile's
    values, ranked by the good/bad density ratio. Categorical params:
    weighted draw by smoothed good-split counts."""

    def __init__(self, param_space: Dict, metric: str, mode: str = "max", *,
                 n_initial: int = 5, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        assert mode in ("max", "min")
        self.space = param_space
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._configs: Dict[str, Dict] = {}
        self._scores: List = []   # (score, config)

    def _random_config(self) -> Dict:
        cfg = {}
        for k, v in self.space.items():
            if isinstance(v, GridSearch):
                cfg[k] = self.rng.choice(v.values)
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self.rng)
            else:
                cfg[k] = v
        return cfg

    def suggest(self, trial_id: str) -> Dict:
        if len(self._scores) < self.n_initial:
            cfg = self._random_config()
        else:
            cfg = self._tpe_config()
        self._configs[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[Dict]):
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "max" else -float(value)
        self._scores.append((score, cfg))

    # -- TPE internals -----------------------------------------------------

    def _split(self):
        ranked = sorted(self._scores, key=lambda sc: sc[0], reverse=True)
        n_good = max(1, int(len(ranked) * self.gamma))
        return ([c for _, c in ranked[:n_good]],
                [c for _, c in ranked[n_good:]] or [c for _, c in ranked])

    @staticmethod
    def _kde_logpdf(x: float, points: List[float], bandwidth: float) -> float:
        if not points:
            return 0.0
        acc = 0.0
        for p in points:
            z = (x - p) / bandwidth
            acc += math.exp(-0.5 * z * z)
        return math.log(max(acc / (len(points) * bandwidth), 1e-300))

    def _tpe_config(self) -> Dict:
        good, bad = self._split()
        cfg = {}
        for k, v in self.space.items():
            if isinstance(v, Categorical) or isinstance(v, GridSearch):
                values = v.categories if isinstance(v, Categorical) else v.values
                counts = {c: 1.0 for c in values}   # +1 smoothing
                for g in good:
                    if g.get(k) in counts:
                        counts[g[k]] += 1.0
                total = sum(counts.values())
                r = self.rng.random() * total
                acc = 0.0
                for c, w in counts.items():
                    acc += w
                    if r <= acc:
                        cfg[k] = c
                        break
            elif isinstance(v, (Uniform, LogUniform, RandInt)):
                log_scale = isinstance(v, LogUniform)

                def to_x(val):
                    return math.log(val) if log_scale else float(val)

                gx = [to_x(g[k]) for g in good if k in g]
                bx = [to_x(b[k]) for b in bad if k in b]
                lo, hi = ((v.log_low, v.log_high) if log_scale
                          else (v.low, v.high))
                span = max(hi - lo, 1e-12)
                # Scott's rule on the GOOD set (what BOHB's KDE does):
                # bandwidth tracks the spread of the good observations, so
                # a concentrated good set means tight candidates. Floor at
                # 1% of span (degenerate/singleton sets), cap at the old
                # diffuse span/sqrt(n) so sparse sets stay exploratory.
                if len(gx) >= 2:
                    mean = sum(gx) / len(gx)
                    std = math.sqrt(sum((g - mean) ** 2 for g in gx)
                                    / (len(gx) - 1))
                    bw = std * len(gx) ** -0.2
                else:
                    bw = span / 2.0
                bw = min(max(bw, span * 0.01, 1e-6),
                         span / max(math.sqrt(len(gx) or 1), 1.0))
                best, best_ratio = None, -math.inf
                for _ in range(self.n_candidates):
                    base = self.rng.choice(gx) if gx else self.rng.uniform(lo, hi)
                    x = self.rng.gauss(base, bw)
                    # Reflect at the bounds instead of clamping: a clamp
                    # piles an atom of candidate density on the boundary,
                    # and one noisy-good boundary observation then locks
                    # the whole search onto it.
                    for _r in range(8):
                        if x < lo:
                            x = 2 * lo - x
                        elif x > hi:
                            x = 2 * hi - x
                        else:
                            break
                    x = min(max(x, lo), hi)
                    ratio = (self._kde_logpdf(x, gx, bw)
                             - self._kde_logpdf(x, bx, bw))
                    if ratio > best_ratio:
                        best, best_ratio = x, ratio
                val = math.exp(best) if log_scale else best
                if isinstance(v, RandInt):
                    val = min(max(int(round(val)), v.low), v.high - 1)
                else:
                    val = min(max(val, v.low), v.high)
                cfg[k] = val
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self.rng)
            else:
                cfg[k] = v
        return cfg


class BOHBSearcher(TPESearcher):
    """BOHB (Bayesian Optimization + HyperBand, Falkner et al. 2018):
    HyperBand's multi-fidelity budgets with a TPE model in place of random
    sampling. Reference analog: tune/search/bohb/bohb_search.py (TuneBOHB
    via the ConfigSpace sampler) — native here, no dependency.

    Observations pool PER BUDGET (trials a HyperBand scheduler stops at a
    rung complete with that rung's budget in their last result); the model
    draws from the highest budget that has accumulated
    `min_points_in_model` observations, so high-fidelity evidence
    dominates as it appears. With probability `random_fraction` (and until
    any pool is large enough) configs stay random — BOHB's exploration
    floor, which also guarantees every region keeps nonzero density."""

    def __init__(self, param_space: Dict, metric: str, mode: str = "max", *,
                 budget_key: str = "training_iteration",
                 min_points_in_model: Optional[int] = None,
                 random_fraction: float = 1 / 3, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        super().__init__(param_space, metric, mode, n_initial=0,
                         gamma=gamma, n_candidates=n_candidates, seed=seed)
        self.budget_key = budget_key
        self.min_points = (min_points_in_model
                           if min_points_in_model is not None
                           else len(param_space) + 2)
        self.random_fraction = random_fraction
        self._pools: Dict[float, List] = {}

    def suggest(self, trial_id: str) -> Dict:
        pool = self._model_pool()
        if pool is None or self.rng.random() < self.random_fraction:
            cfg = self._random_config()
        else:
            # TPE internals read self._scores; point them at the chosen
            # budget's pool for this draw.
            self._scores = pool
            cfg = self._tpe_config()
        self._configs[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[Dict]):
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "max" else -float(value)
        budget = float(result.get(self.budget_key) or 1.0)
        self._pools.setdefault(budget, []).append((score, cfg))

    def _model_pool(self) -> Optional[List]:
        for budget in sorted(self._pools, reverse=True):
            if len(self._pools[budget]) >= self.min_points:
                return self._pools[budget]
        return None


def generate_variants(param_space: Dict, num_samples: int, seed: int = 0
                      ) -> List[Dict]:
    """Expand grid axes × `num_samples` random draws of stochastic domains."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grids = [param_space[k].values for k in grid_keys]
    variants: List[Dict] = []
    for combo in itertools.product(*grids) if grids else [()]:
        for _ in range(num_samples):
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
