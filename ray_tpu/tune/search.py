"""Search spaces and trial generation.

Reference analog: python/ray/tune/search/ (sample.py domains,
basic_variant.py BasicVariantGenerator). Grid axes expand combinatorially;
stochastic domains sample `num_samples` times.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def generate_variants(param_space: Dict, num_samples: int, seed: int = 0
                      ) -> List[Dict]:
    """Expand grid axes × `num_samples` random draws of stochastic domains."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grids = [param_space[k].values for k in grid_keys]
    variants: List[Dict] = []
    for combo in itertools.product(*grids) if grids else [()]:
        for _ in range(num_samples):
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
