"""Experiment snapshots: persist and restore a tuning run.

Reference analog: python/ray/tune/execution/experiment_state.py (periodic
experiment-state snapshots) + Tuner.restore (tuner.py). A snapshot is two
files in the run's storage dir:

    trainable.pkl           — the cloudpickled trainable (saved once)
    experiment_state.pkl    — pickled dict: settings + per-trial state

Restore rebuilds the Tuner: TERMINATED/ERRORED trials keep their results;
PENDING trials re-queue; RUNNING trials (interrupted mid-flight) re-queue
and, when they have a persisted checkpoint, restart from it (the trainable
sees checkpoint_dir exactly as after a PBT exploit)."""

from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional

TRAINABLE_FILE = "trainable.pkl"
STATE_FILE = "experiment_state.pkl"


def save_trainable(storage_dir: str, trainable) -> None:
    import cloudpickle

    path = os.path.join(storage_dir, TRAINABLE_FILE)
    if not os.path.exists(path):
        with open(path + ".tmp", "wb") as f:
            f.write(cloudpickle.dumps(trainable))
        os.replace(path + ".tmp", path)


def save_snapshot(storage_dir: str, trials: List, settings: Dict) -> None:
    """Atomic write of the current trial table."""
    state = {
        "settings": settings,
        "trials": [{
            "trial_id": t.trial_id,
            "config": t.config,
            "status": t.status,
            "last_result": t.last_result,
            "history": t.history,
            "checkpoint_dir": t.checkpoint_dir,
            "error": t.error,
            "restarts": t.restarts,
        } for t in trials],
    }
    path = os.path.join(storage_dir, STATE_FILE)
    with open(path + ".tmp", "wb") as f:
        pickle.dump(state, f)
    os.replace(path + ".tmp", path)


def load_snapshot(storage_dir: str) -> Optional[Dict]:
    path = os.path.join(storage_dir, STATE_FILE)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return pickle.load(f)


def load_trainable(storage_dir: str):
    import cloudpickle

    with open(os.path.join(storage_dir, TRAINABLE_FILE), "rb") as f:
        return cloudpickle.loads(f.read())


def restorable(storage_dir: str) -> bool:
    return (os.path.exists(os.path.join(storage_dir, STATE_FILE))
            and os.path.exists(os.path.join(storage_dir, TRAINABLE_FILE)))
