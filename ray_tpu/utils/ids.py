"""Binary IDs for tasks/objects/actors/nodes/workers.

Reference analog: src/ray/common/id.h (TaskID/ObjectID/ActorID/NodeID...).
All IDs are fixed-size random byte strings; ObjectIDs for task returns are
derived deterministically from the task id + return index so any process can
compute them.
"""

from __future__ import annotations

import hashlib
import os

ID_SIZE = 20


class BaseID:
    __slots__ = ("_bytes",)
    _prefix = "id"

    def __init__(self, id_bytes: bytes):
        assert len(id_bytes) == ID_SIZE, f"bad id length {len(id_bytes)}"
        self._bytes = bytes(id_bytes)

    @classmethod
    def generate(cls):
        return cls(os.urandom(ID_SIZE))

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __eq__(self, other):
        return isinstance(other, BaseID) and other._bytes == self._bytes

    def __hash__(self):
        return hash(self._bytes)

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:12]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    @classmethod
    def for_task_return(cls, task_id: "TaskID", index: int) -> "ObjectID":
        h = hashlib.sha1(task_id.binary() + index.to_bytes(4, "little")).digest()
        return cls(h[:ID_SIZE])


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass
