"""Process-debugging helpers shared by the runtime entry points."""

from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Dict, List, Optional


def register_stack_dump_signal() -> None:
    """SIGUSR1 dumps every thread's stack to stderr — the first tool for
    diagnosing a hung GCS/raylet/worker without restarting it (the stderr
    of runtime processes lands in the session's per-process log file)."""
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)


def render_stacks(label: str = "") -> dict:
    """Snapshot every thread's stack, annotated with blocked-on context.

    The structured (JSON-able) analog of faulthandler's SIGUSR1 dump:
    `sys._current_frames()` plus, per thread, the live blocked-on record
    from `core.blocked` (what the thread is waiting for — object get,
    collective op, channel read) and the task/actor the thread is
    executing. This is what the `dump_stacks` RPC returns and what
    `scripts stack --cluster` / dashboard `/api/stacks` render.
    """
    from ray_tpu.core import blocked as blocked_mod

    threads_by_ident = {t.ident: t for t in threading.enumerate()}
    blocked = blocked_mod.snapshot()
    out = []
    # The snapshot contains this thread's own frame (a cycle) and keeps any
    # concurrently-returning frame alive with its locals until collected —
    # enough to pin channel buffers and wedge a ring writer. clear() drops
    # every frame ref the moment rendering is done.
    frames = sys._current_frames()
    try:
        for ident, frame in frames.items():
            t = threads_by_ident.get(ident)
            rec = {
                "ident": ident,
                "name": t.name if t else f"thread-{ident}",
                "daemon": bool(t.daemon) if t else False,
                "frames": [ln.rstrip("\n")
                           for ln in traceback.format_stack(frame)],
            }
            b = blocked.get(ident)
            if b:
                rec["blocked_on"] = b
            ctx = blocked_mod.task_context(ident)
            if ctx:
                rec["task"] = ctx
            out.append(rec)
        frame = None
    finally:
        frames.clear()
    return {"pid": os.getpid(), "label": label, "threads": out}


def _describe_blocked(b: dict) -> str:
    import time as _time

    kind = b.get("kind", "?")
    d = b.get("detail", {})
    age = _time.time() - b.get("since", _time.time())
    if kind == "object_get":
        parts = [f"object {d.get('oid', '?')}"]
        if d.get("owner"):
            parts.append(f"owner {d['owner']}")
        if d.get("target_name"):
            parts.append(f"result of {d['target_name']!r}")
        if d.get("target_actor"):
            parts.append(f"actor {d['target_actor']}")
        what = ", ".join(parts)
        return f"blocked on get({what}) for {age:.1f}s"
    if kind == "collective_op":
        return (f"blocked in collective group {d.get('group', '?')!r} "
                f"op #{d.get('op_id', '?')} "
                f"(rank {d.get('rank', '?')}/{d.get('world_size', '?')}) "
                f"for {age:.1f}s")
    if kind == "channel_read":
        return (f"blocked on channel {d.get('channel', '?')} read "
                f"(version {d.get('version', '?')}) for {age:.1f}s")
    return f"blocked on {kind} for {age:.1f}s"


def format_stacks(processes: List[dict], dedupe: bool = True) -> str:
    """Render `render_stacks()` results as text, deduping identical stacks.

    Idle pool threads all parked on the same `wait()` line are the noise
    of a stack dump; grouping by (frames, blocked-on description) keeps
    the one-screen signal. Blocked/task-annotated threads sort first.
    """
    lines: List[str] = []
    for proc in processes:
        label = proc.get("label") or f"pid {proc.get('pid')}"
        lines.append(f"=== {label} (pid {proc.get('pid')}) ===")
        groups: Dict[tuple, dict] = {}
        for t in proc.get("threads", []):
            desc = _describe_blocked(t["blocked_on"]) \
                if t.get("blocked_on") else ""
            key = (tuple(t.get("frames", ())), desc) if dedupe \
                else (t["ident"],)
            g = groups.setdefault(key, {"threads": [], "t": t,
                                        "desc": desc})
            g["threads"].append(t)
        ordered = sorted(
            groups.values(),
            key=lambda g: (0 if g["desc"] else (1 if g["t"].get("task")
                                                else 2)))
        for g in ordered:
            t = g["t"]
            names = ", ".join(x["name"] for x in g["threads"][:4])
            extra = len(g["threads"]) - 4
            if extra > 0:
                names += f", +{extra} more"
            header = f"-- thread {names}"
            task = t.get("task")
            if task:
                who = task.get("name") or task.get("task_id")
                header += f" [running {who}"
                if task.get("actor_id"):
                    header += f" on actor {task['actor_id']}"
                header += "]"
            lines.append(header)
            if g["desc"]:
                lines.append(f"   {g['desc']}")
            for fr in t.get("frames", []):
                lines.append("  " + fr.replace("\n", "\n  "))
        lines.append("")
    return "\n".join(lines)
