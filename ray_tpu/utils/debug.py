"""Process-debugging helpers shared by the runtime entry points."""

from __future__ import annotations


def register_stack_dump_signal() -> None:
    """SIGUSR1 dumps every thread's stack to stderr — the first tool for
    diagnosing a hung GCS/raylet/worker without restarting it (the stderr
    of runtime processes lands in the session's per-process log file)."""
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)
