"""ray_tpu: a TPU-native distributed ML framework.

A brand-new implementation of the Ray programming model (tasks, actors,
objects, placement groups) and ML stack (train/data/tune/serve/llm/rl),
designed TPU-first: JAX/XLA/Pallas for compute, `jax.lax` collectives over
ICI for communication, and a scheduler that understands TPU chips and slices.
See SURVEY.md at the repo root for the structural map to the reference.
"""

from ray_tpu.core.api import (  # noqa: F401
    available_resources,
    cancel,
    cluster_resources,
    free,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.exceptions import (  # noqa: F401
    ActorDiedError,
    ActorError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.placement_group import (  # noqa: F401
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.core.generator import ObjectRefGenerator  # noqa: F401
from ray_tpu.core.object_ref import ObjectRef  # noqa: F401

__version__ = "0.1.0"
