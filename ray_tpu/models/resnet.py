"""ResNet-50 (and friends) in pure JAX — the DDP reference-config model.

Reference analog: the ResNet-50/CIFAR-10 TorchTrainer DDP config
(BASELINE.json configs[0]). Functional: `apply(params, state, x, train)`
returns (logits, new_state) where state carries batch-norm running stats.
NHWC layout (TPU-native; channels-last feeds the MXU's 128-lane dimension).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

STAGES = {
    "resnet18": ([2, 2, 2, 2], False),
    "resnet34": ([3, 4, 6, 3], False),
    "resnet50": ([3, 4, 6, 3], True),
    "resnet101": ([3, 4, 23, 3], True),
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: str = "resnet50"
    num_classes: int = 10
    width: int = 64
    small_inputs: bool = True     # CIFAR stem (3x3, no maxpool)
    dtype: Any = jnp.float32
    bn_momentum: float = 0.9


def _conv(params_key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return (jax.random.normal(params_key, (kh, kw, cin, cout), jnp.float32)
            * jnp.sqrt(2.0 / fan_in))


def _bn_init(c):
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def _bn_state(c):
    return {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}


def conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def batch_norm(x, p, s, train: bool, momentum: float):
    if train:
        mean = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.var(x, axis=(0, 1, 2))
        new_s = {"mean": momentum * s["mean"] + (1 - momentum) * mean,
                 "var": momentum * s["var"] + (1 - momentum) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jax.lax.rsqrt(var + 1e-5).astype(x.dtype)
    out = (x - mean.astype(x.dtype)) * inv * p["scale"].astype(x.dtype) \
        + p["bias"].astype(x.dtype)
    return out, new_s


def init(config: ResNetConfig, key) -> Tuple[Dict, Dict]:
    blocks, bottleneck = STAGES[config.depth]
    keys = iter(jax.random.split(key, 256))
    w = config.width
    params: Dict = {}
    state: Dict = {}
    stem_k = 3 if config.small_inputs else 7
    params["stem"] = {"conv": _conv(next(keys), stem_k, stem_k, 3, w),
                      "bn": _bn_init(w)}
    state["stem"] = _bn_state(w)
    cin = w
    for si, n in enumerate(blocks):
        cmid = w * (2 ** si)
        cout = cmid * (4 if bottleneck else 1)
        for bi in range(n):
            stride = 2 if (bi == 0 and si > 0) else 1
            name = f"s{si}b{bi}"
            bp: Dict = {}
            bs: Dict = {}
            if bottleneck:
                bp["conv1"] = _conv(next(keys), 1, 1, cin, cmid)
                bp["conv2"] = _conv(next(keys), 3, 3, cmid, cmid)
                bp["conv3"] = _conv(next(keys), 1, 1, cmid, cout)
                for i, c in (("1", cmid), ("2", cmid), ("3", cout)):
                    bp[f"bn{i}"] = _bn_init(c)
                    bs[f"bn{i}"] = _bn_state(c)
            else:
                bp["conv1"] = _conv(next(keys), 3, 3, cin, cmid)
                bp["conv2"] = _conv(next(keys), 3, 3, cmid, cout)
                for i, c in (("1", cmid), ("2", cout)):
                    bp[f"bn{i}"] = _bn_init(c)
                    bs[f"bn{i}"] = _bn_state(c)
            if stride != 1 or cin != cout:
                bp["proj"] = _conv(next(keys), 1, 1, cin, cout)
                bp["proj_bn"] = _bn_init(cout)
                bs["proj_bn"] = _bn_state(cout)
            params[name] = bp
            state[name] = bs
            cin = cout
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, config.num_classes)) * 0.01,
        "b": jnp.zeros((config.num_classes,))}
    return params, state


def apply(params: Dict, state: Dict, x: jax.Array, config: ResNetConfig,
          train: bool = True) -> Tuple[jax.Array, Dict]:
    """x: (n, h, w, 3) -> logits (n, classes), new batch-norm state."""
    blocks, bottleneck = STAGES[config.depth]
    x = x.astype(config.dtype)
    new_state: Dict = {}
    p = params["stem"]
    x = conv(x, p["conv"], stride=1 if config.small_inputs else 2)
    x, new_state["stem"] = batch_norm(x, p["bn"], state["stem"], train,
                                      config.bn_momentum)
    x = jax.nn.relu(x)
    if not config.small_inputs:
        x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                                  (1, 2, 2, 1), "SAME")
    for si, n in enumerate(blocks):
        for bi in range(n):
            stride = 2 if (bi == 0 and si > 0) else 1
            name = f"s{si}b{bi}"
            bp, bs = params[name], state[name]
            ns: Dict = {}
            shortcut = x
            if bottleneck:
                y = conv(x, bp["conv1"])
                y, ns["bn1"] = batch_norm(y, bp["bn1"], bs["bn1"], train,
                                          config.bn_momentum)
                y = jax.nn.relu(y)
                y = conv(y, bp["conv2"], stride)
                y, ns["bn2"] = batch_norm(y, bp["bn2"], bs["bn2"], train,
                                          config.bn_momentum)
                y = jax.nn.relu(y)
                y = conv(y, bp["conv3"])
                y, ns["bn3"] = batch_norm(y, bp["bn3"], bs["bn3"], train,
                                          config.bn_momentum)
            else:
                y = conv(x, bp["conv1"], stride)
                y, ns["bn1"] = batch_norm(y, bp["bn1"], bs["bn1"], train,
                                          config.bn_momentum)
                y = jax.nn.relu(y)
                y = conv(y, bp["conv2"])
                y, ns["bn2"] = batch_norm(y, bp["bn2"], bs["bn2"], train,
                                          config.bn_momentum)
            if "proj" in bp:
                shortcut = conv(x, bp["proj"], stride)
                shortcut, ns["proj_bn"] = batch_norm(
                    shortcut, bp["proj_bn"], bs["proj_bn"], train,
                    config.bn_momentum)
            x = jax.nn.relu(y + shortcut)
            new_state[name] = ns
    x = jnp.mean(x, axis=(1, 2))
    logits = x.astype(jnp.float32) @ params["head"]["w"] + params["head"]["b"]
    return logits, new_state


def loss_fn(params, state, batch, config: ResNetConfig):
    logits, new_state = apply(params, state, batch["image"], config, train=True)
    labels = batch["label"]
    loss = -jnp.mean(jnp.take_along_axis(
        jax.nn.log_softmax(logits), labels[:, None], axis=-1))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc, "state": new_state}
