"""Llama-3-family decoder: the flagship model, pure-JAX and mesh-native.

The reference serves this family through external engines (vLLM for serving,
torch for training — SURVEY §2.3 ray.llm/ray.train rows). Here the model is a
first-class citizen: parameters are a pytree with logical-axis annotations
(ray_tpu.parallel.sharding), the layer stack is a `lax.scan` over stacked
weights (one-layer compile, O(1) HLO size in depth), attention dispatches to
XLA-fused reference, Pallas flash (serving), or ring attention (sp>1), and
the same definition drives training (FSDP/TP/SP) and inference (TP + paged
KV) by swapping rule tables.

Architecture: RMSNorm (pre-norm), RoPE, GQA, SwiGLU — Llama-3 conventions.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies, swiglu


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 8
    d_ff: int = 8192
    max_seq: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "full": recompute everything in backward (min memory, ~fwd again of
    # extra FLOPs). "dots": save matmul outputs without batch dims
    # (projections/MLP), recompute elementwise + attention scores — the
    # usual TPU sweet spot when HBM allows (scaling-book remat recipe).
    remat_policy: str = "full"     # full | dots
    attention_impl: str = "auto"   # reference | flash | ring
    sp_axis: str = "sp"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b(**overrides) -> "LlamaConfig":
        base = dict(vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
                    n_kv_heads=8, d_ff=14336, rope_theta=500000.0)
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        base = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=128)
        base.update(overrides)
        return LlamaConfig(**base)

    def num_params(self) -> int:
        d, f, v, L = self.d_model, self.d_ff, self.vocab_size, self.n_layers
        hd = self.head_dim
        attn = d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd + self.n_heads * hd * d
        mlp = 3 * d * f
        per_layer = attn + mlp + 2 * d
        return v * d + L * per_layer + d + d * v

    def flops_per_token(self, seq: int) -> float:
        """Training FLOPs/token (fwd+bwd ~= 6*N + attention term)."""
        n = self.num_params() - self.vocab_size * self.d_model  # non-embedding
        attn_flops = 12 * self.n_layers * self.d_model * seq  # 2*2*3 * L * d * s
        return 6.0 * n + attn_flops


# ---------------------------------------------------------------- parameters

def init_params(config: LlamaConfig, key: jax.Array) -> Dict:
    d, f, v = config.d_model, config.d_ff, config.vocab_size
    hd, H, K, L = config.head_dim, config.n_heads, config.n_kv_heads, config.n_layers
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(config.dtype)

    ks = jax.random.split(k_layers, 7)

    def stack(key, shape, fan_in):
        return dense(key, (L,) + shape, fan_in)

    params = {
        "embed": dense(k_embed, (v, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype=config.dtype),
            "wq": stack(ks[0], (d, H * hd), d),
            "wk": stack(ks[1], (d, K * hd), d),
            "wv": stack(ks[2], (d, K * hd), d),
            "wo": stack(ks[3], (H * hd, d), H * hd),
            "mlp_norm": jnp.ones((L, d), dtype=config.dtype),
            "w_gate": stack(ks[4], (d, f), d),
            "w_up": stack(ks[5], (d, f), d),
            "w_down": stack(ks[6], (f, d), f),
        },
        "final_norm": jnp.ones((d,), dtype=config.dtype),
        "lm_head": dense(k_head, (d, v), d),
    }
    return params


def param_logical_axes(config: LlamaConfig) -> Dict:
    """Logical axis names per parameter (see parallel/sharding.py rules)."""
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", None),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


# ---------------------------------------------------------------- forward

def _attention_dispatch(config: LlamaConfig, q, k, v):
    impl = config.attention_impl
    if impl == "ring":
        from functools import partial as _partial

        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel.mesh import current_mesh
        from ray_tpu.parallel.ring import ring_attention

        mesh = current_mesh()
        if mesh is None:
            raise RuntimeError(
                "attention_impl='ring' needs an ambient mesh: wrap the step "
                "in ray_tpu.parallel.mesh.use_mesh(mesh)")
        spec = P(("dp", "fsdp", "ep"), config.sp_axis, "tp", None)
        # check_vma=False: the flash kernel's interpret-mode discharge hits
        # a jax vma propagation gap on dynamic_slice indices (jax suggests
        # exactly this workaround); Mosaic lowering is unaffected.
        fn = shard_map(
            _partial(ring_attention, axis_name=config.sp_axis, causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return fn(q, k, v)
    return attention(q, k, v, causal=True, impl=impl)


def attention_sublayer(config, x, p, cos, sin):
    """Pre-norm GQA attention block with residual. Shared by every decoder
    family in models/ (config needs head_dim/n_heads/n_kv_heads/norm_eps and
    the attention_impl fields _attention_dispatch reads)."""
    from ray_tpu.parallel.sharding import constrain

    b, s, d = x.shape
    hd, H, K = config.head_dim, config.n_heads, config.n_kv_heads
    h = rms_norm(x, p["attn_norm"], config.norm_eps)
    # Constrain every projection OUTPUT to batch-sharded: with fsdp-sharded
    # weights, GSPMD then all-gathers the weights (the FSDP recipe) instead
    # of resharding the activation embed-over-fsdp, which degenerates into
    # an involuntary full rematerialization per layer.
    q = constrain((h @ p["wq"]).reshape(b, s, H, hd),
                  ("batch", "seq", "heads", None))
    k = constrain((h @ p["wk"]).reshape(b, s, K, hd),
                  ("batch", "seq", "kv_heads", None))
    v = constrain((h @ p["wv"]).reshape(b, s, K, hd),
                  ("batch", "seq", "kv_heads", None))
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn_out = _attention_dispatch(config, q, k, v)
    out = attn_out.reshape(b, s, H * hd) @ p["wo"]
    return x + constrain(out, ("batch", "seq", None))


def next_token_ce(logits: jax.Array, targets: jax.Array,
                  mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token cross entropy; mask (same shape as targets) optional."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return -ll.mean()


# Per-layer param layout INSIDE the scan: "embed" gathered (None) so each
# layer's weights are explicitly all-gathered over fsdp right before use —
# the FSDP recipe (gather weights, compute, discard; grads reduce-scatter
# back through the constraint's transpose). Left implicit, GSPMD instead
# reshards the batch-sharded activation embed-over-fsdp, which degenerates
# into an involuntary full rematerialization per layer. tp axes stay.
_LAYER_GATHER_AXES = {
    "attn_norm": (None,),
    "wq": (None, "heads"),
    "wk": (None, "kv_heads"),
    "wv": (None, "kv_heads"),
    "wo": ("heads", None),
    "mlp_norm": (None,),
    "w_gate": (None, "mlp"),
    "w_up": (None, "mlp"),
    "w_down": ("mlp", None),
}


def _gather_layer_params(p, extra_axes=None):
    from ray_tpu.parallel.sharding import constrain

    axes = dict(_LAYER_GATHER_AXES)
    if extra_axes:
        axes.update(extra_axes)
    return {k: (constrain(v, axes[k]) if k in axes else v)
            for k, v in p.items()}


def _layer(config: LlamaConfig, x, layer_params, cos, sin):
    """One decoder layer. x: (b, s, d)."""
    from ray_tpu.parallel.sharding import constrain

    p = _gather_layer_params(layer_params)
    # Keep the loop-carried activation on (batch, seq, None) inside the
    # scan: left to propagation, GSPMD picks a d-over-fsdp carry sharding
    # (resharding activations instead of all-gathering weights) and
    # full-rematerializes every layer.
    x = constrain(x, ("batch", "seq", None))
    x = attention_sublayer(config, x, p, cos, sin)
    h = rms_norm(x, p["mlp_norm"], config.norm_eps)
    gate = constrain(h @ p["w_gate"], ("batch", "seq", "mlp"))
    up = constrain(h @ p["w_up"], ("batch", "seq", "mlp"))
    x = x + constrain(swiglu(gate, up) @ p["w_down"], ("batch", "seq", None))
    return x


def backbone(params: Dict, tokens: jax.Array, config: LlamaConfig) -> jax.Array:
    """tokens: (b, s) int32 -> final-norm hidden states (b, s, d) in
    config.dtype — everything `forward` computes except the lm_head
    projection. Value heads and reward models (rlhf/) hang off this."""
    from ray_tpu.parallel.sharding import constrain

    cos, sin = rope_frequencies(config.head_dim, config.max_seq, config.rope_theta)
    # Deliberately all-gather the table's fsdp (embed) factor before the
    # lookup (rows stay vocab-sharded over tp); the backward reduce-scatters
    # the table grad through the constraint's transpose. Left implicit,
    # GSPMD wants the gather cotangent embed-over-fsdp and falls back to an
    # involuntary full rematerialization.
    table = constrain(params["embed"], ("vocab", None))
    x = table[tokens].astype(config.dtype)
    x = constrain(x, ("batch", "seq", None))

    layer_fn = partial(_layer, config)
    if config.remat:
        if config.remat_policy not in ("full", "dots", "flash"):
            raise ValueError(
                f"remat_policy must be 'full', 'dots' or 'flash', "
                f"got {config.remat_policy!r}")
        # "flash": save ONLY the flash-attention kernel outputs (out +
        # lse, tagged in ops/attention.py) — O(s) extra memory per layer,
        # and the backward skips re-running the O(s^2) forward kernel
        # (its other residuals, q/k/v, are cheap dot recomputes from the
        # saved layer input). The long-context policy: "dots" busts HBM
        # past ~8k, full remat pays the quadratic kernel twice.
        policy = {
            "full": jax.checkpoint_policies.nothing_saveable,
            "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "flash": jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"),
        }[config.remat_policy]
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    def scan_body(x, layer_params):
        return layer_fn(x, layer_params, cos, sin), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    return constrain(x, ("batch", "seq", None))


def forward(params: Dict, tokens: jax.Array, config: LlamaConfig) -> jax.Array:
    """tokens: (b, s) int32 -> logits (b, s, vocab) float32."""
    from ray_tpu.parallel.sharding import constrain

    x = backbone(params, tokens, config)
    # lm_head: gather the fsdp (embed/contracting) factor, keep vocab on tp.
    lm_head = constrain(params["lm_head"], (None, "vocab"))
    logits = (x @ lm_head.astype(config.dtype)).astype(jnp.float32)
    logits = constrain(logits, ("batch", "seq", "vocab"))
    return logits


def loss_fn(params: Dict, batch: Dict[str, jax.Array],
            config: LlamaConfig) -> Tuple[jax.Array, Dict]:
    """batch: {"tokens": (b, s+1) int32} -> next-token cross entropy."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inputs, config)
    mask = batch.get("mask")
    loss = next_token_ce(logits, targets,
                         mask[:, 1:] if mask is not None else None)
    return loss, {"loss": loss, "tokens": jnp.array(targets.size, jnp.float32)}
