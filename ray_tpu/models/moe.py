"""Mixtral-family sparse MoE decoder: expert parallelism done the TPU way.

The reference has no expert parallelism of its own — EP exists only inside
vLLM (SURVEY §2.4 EP row: "Absent (vLLM-internal)"). Here it is first-class:
experts are a stacked weight dimension with logical axis "expert" sharded
over the `ep` mesh axis, and token routing is expressed as dense
dispatch/combine einsums over a static per-expert capacity. Under GSPMD this
compiles to the canonical all-to-all dispatch → grouped matmul → all-to-all
combine schedule over ICI; shapes stay static (XLA/MXU-friendly) and dropped
tokens fall out of the capacity mask instead of dynamic shapes.

Architecture: Llama-3 attention (RMSNorm/RoPE/GQA) with the dense SwiGLU MLP
replaced by a top-k softmax router + E SwiGLU experts (Mixtral conventions:
top-k gates renormalized to sum to 1). Aux losses: switch-style load
balancing and router z-loss.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import attention_sublayer, next_token_ce
from ray_tpu.ops.layers import rms_norm, rope_frequencies, swiglu


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    d_model: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 8
    d_ff: int = 4096            # per-expert FFN width
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    max_seq: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    attention_impl: str = "auto"
    sp_axis: str = "sp"
    balance_loss_coef: float = 0.01
    z_loss_coef: float = 1e-3

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def capacity(self, seq: int) -> int:
        """Static per-expert token capacity for a (batch-row, seq) shard."""
        cap = self.capacity_factor * self.top_k * seq / self.n_experts
        return max(1, math.ceil(cap))

    @staticmethod
    def mixtral_8x7b(**overrides) -> "MoEConfig":
        base = dict(vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
                    n_kv_heads=8, d_ff=14336, n_experts=8, top_k=2,
                    rope_theta=1e6)
        base.update(overrides)
        return MoEConfig(**base)

    @staticmethod
    def tiny(**overrides) -> "MoEConfig":
        base = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=96, n_experts=4, top_k=2, max_seq=128)
        base.update(overrides)
        return MoEConfig(**base)

    def num_params(self) -> int:
        d, f, v, L, E = (self.d_model, self.d_ff, self.vocab_size,
                         self.n_layers, self.n_experts)
        hd = self.head_dim
        attn = (d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd
                + self.n_heads * hd * d)
        moe = d * E + 3 * E * d * f
        return v * d + L * (attn + moe + 2 * d) + d + d * v

    def active_params(self) -> int:
        """Parameters touched per token (top-k of E experts)."""
        d, f, L = self.d_model, self.d_ff, self.n_layers
        hd = self.head_dim
        attn = (d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd
                + self.n_heads * hd * d)
        moe = d * self.n_experts + 3 * self.top_k * d * f
        return self.vocab_size * d + L * (attn + moe + 2 * d) + d + d * self.vocab_size

    def flops_per_token(self, seq: int) -> float:
        n = self.active_params() - self.vocab_size * self.d_model
        return 6.0 * n + 12 * self.n_layers * self.d_model * seq


# ---------------------------------------------------------------- parameters

def init_params(config: MoEConfig, key: jax.Array) -> Dict:
    d, f, v = config.d_model, config.d_ff, config.vocab_size
    hd, H, K = config.head_dim, config.n_heads, config.n_kv_heads
    L, E = config.n_layers, config.n_experts
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(config.dtype)

    ks = jax.random.split(k_layers, 9)

    params = {
        "embed": dense(k_embed, (v, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype=config.dtype),
            "wq": dense(ks[0], (L, d, H * hd), d),
            "wk": dense(ks[1], (L, d, K * hd), d),
            "wv": dense(ks[2], (L, d, K * hd), d),
            "wo": dense(ks[3], (L, H * hd, d), H * hd),
            "mlp_norm": jnp.ones((L, d), dtype=config.dtype),
            # Router stays float32: routing decisions are precision-sensitive.
            "router": jax.random.normal(ks[4], (L, d, E), dtype=jnp.float32)
                      * (1.0 / math.sqrt(d)),
            "w_gate": dense(ks[5], (L, E, d, f), d),
            "w_up": dense(ks[6], (L, E, d, f), d),
            "w_down": dense(ks[7], (L, E, f, d), f),
        },
        "final_norm": jnp.ones((d,), dtype=config.dtype),
        "lm_head": dense(k_head, (d, v), d),
    }
    return params


def param_logical_axes(config: MoEConfig) -> Dict:
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", None),
            # Router is tiny; replicate so every shard routes locally.
            "router": ("layers", "embed", None),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


# ---------------------------------------------------------------- MoE block

def moe_block(config: MoEConfig, x: jax.Array, router: jax.Array,
              w_gate: jax.Array, w_up: jax.Array,
              w_down: jax.Array) -> Tuple[jax.Array, Dict]:
    """Top-k routed expert FFN with static capacity.

    x: (b, s, d); router: (d, E); w_gate/w_up: (E, d, f); w_down: (E, f, d).
    Returns (out (b, s, d), aux losses dict). Dropped tokens (expert over
    capacity) contribute zero — the residual connection carries them.
    """
    b, s, d = x.shape
    E, k = config.n_experts, config.top_k
    C = config.capacity(s)

    logits = x.astype(jnp.float32) @ router              # (b, s, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)      # (b, s, k)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)          # Mixtral renorm

    # (b, s, k, E) one-hot of chosen experts.
    sel = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)
    # Position of each (token, choice) in its expert's queue. Queue order is
    # choice-rank-major: all top-1 routes enqueue before any top-2 route, so
    # over-capacity drops hit lower-ranked choices first.
    sel_rank = sel.transpose(0, 2, 1, 3).reshape(b, k * s, E)
    pos = (jnp.cumsum(sel_rank, axis=1) - 1.0).reshape(b, k, s, E)
    pos = pos.transpose(0, 2, 1, 3)
    within_cap = pos < C
    sel = sel * within_cap
    slot = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)
    # masked_slot[b,s,k,e,c] = 1 iff choice k routes token s to expert e at
    # slot c (sel zeroes the slot collisions of unchosen/overflowed entries).
    masked_slot = slot * sel[..., None]
    # dispatch[b, s, e, c] = 1 iff token s goes to expert e at slot c.
    dispatch = masked_slot.sum(axis=2)
    combine = jnp.einsum("bsk,bskec->bsec", gate_vals, masked_slot)

    from ray_tpu.parallel.sharding import constrain

    # Step the token activations down from batch-over-(dp,fsdp,ep) to
    # batch-over-(dp,fsdp) + ep-replicated BEFORE the dispatch einsum: this
    # is the intended EP collective (an all-gather over ep), and without the
    # explicit hop GSPMD falls back to an involuntary full rematerialization
    # (replicate-everything) to reach the expert layout.
    x = constrain(x, ("moe_batch", "seq", None))
    dispatch = constrain(dispatch, ("moe_batch", "seq", None, None))
    xin = jnp.einsum("bsec,bsd->ebcd", dispatch, x.astype(jnp.float32))
    xin = xin.astype(config.dtype)
    # Expert-parallel layout for the dispatched tokens: experts over ep (the
    # dispatch einsum becomes the all-to-all), batch keeps (dp, fsdp), d
    # replicated so the fsdp-sharded expert weights all-gather (FSDP) rather
    # than forcing a degenerate activation reshard.
    xin = constrain(xin, ("expert", "moe_batch", None, None))
    h = swiglu(jnp.einsum("ebcd,edf->ebcf", xin, w_gate),
               jnp.einsum("ebcd,edf->ebcf", xin, w_up))
    h = constrain(h, ("expert", "moe_batch", None, "mlp"))
    out_e = jnp.einsum("ebcf,efd->ebcd", h, w_down)
    out_e = constrain(out_e, ("expert", "moe_batch", None, None))
    combine = constrain(combine, ("moe_batch", "seq", None, None))
    out = jnp.einsum("bsec,ebcd->bsd", combine,
                     out_e.astype(jnp.float32)).astype(x.dtype)
    # Explicit hop back up: batch-over-(dp,fsdp) -> batch-over-(dp,fsdp,ep)
    # (a slice over ep), mirroring the gather on the way in, so the residual
    # add in _layer sees matching layouts.
    out = constrain(out, ("batch", "seq", None))

    # Switch-transformer load-balancing loss: E * sum_e f_e * P_e, where f_e
    # = fraction of (token, choice) pairs routed to e, P_e = mean router prob.
    f_e = jax.nn.one_hot(expert_idx, E).reshape(b, s * k, E).mean(axis=(0, 1))
    p_e = probs.mean(axis=(0, 1))
    balance = E * jnp.sum(f_e * p_e)
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    dropped = 1.0 - dispatch.sum() / (b * s * k)
    aux = {"balance_loss": balance, "router_z_loss": z_loss,
           "dropped_frac": dropped}
    return out, aux


# ---------------------------------------------------------------- forward

def _layer(config: MoEConfig, x, layer_params, cos, sin):
    from ray_tpu.models.llama import _gather_layer_params
    from ray_tpu.parallel.sharding import constrain

    # Same explicit FSDP weight all-gather as llama._layer; expert weights
    # keep their ep sharding and gather only the fsdp (embed) factor.
    p = _gather_layer_params(layer_params, extra_axes={
        "router": (None, None),
        "w_gate": ("expert", None, "mlp"),
        "w_up": ("expert", None, "mlp"),
        "w_down": ("expert", "mlp", None),
    })
    # Pin the scan carry (see llama._layer: an unpinned carry lets GSPMD
    # pick a d-over-fsdp layout and full-rematerialize every layer).
    x = constrain(x, ("batch", "seq", None))
    x = attention_sublayer(config, x, p, cos, sin)
    h = rms_norm(x, p["mlp_norm"], config.norm_eps)
    moe_out, aux = moe_block(config, h, p["router"], p["w_gate"], p["w_up"],
                             p["w_down"])
    return x + moe_out, aux


def forward(params: Dict, tokens: jax.Array,
            config: MoEConfig) -> Tuple[jax.Array, Dict]:
    """tokens: (b, s) int32 -> (logits (b, s, vocab) f32, mean aux losses)."""
    from ray_tpu.parallel.sharding import constrain

    cos, sin = rope_frequencies(config.head_dim, config.max_seq,
                                config.rope_theta)
    # Gather the table's fsdp factor before the lookup (see llama.forward).
    table = constrain(params["embed"], ("vocab", None))
    x = table[tokens].astype(config.dtype)
    x = constrain(x, ("batch", "seq", None))

    layer_fn = partial(_layer, config)
    if config.remat:
        layer_fn = jax.checkpoint(
            layer_fn, policy=jax.checkpoint_policies.nothing_saveable)

    def scan_body(x, layer_params):
        x, aux = layer_fn(x, layer_params, cos, sin)
        return x, aux

    x, aux = jax.lax.scan(scan_body, x, params["layers"])
    aux = jax.tree.map(jnp.mean, aux)  # mean over layers
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    x = constrain(x, ("batch", "seq", None))
    lm_head = constrain(params["lm_head"], (None, "vocab"))
    logits = (x @ lm_head.astype(config.dtype)).astype(jnp.float32)
    logits = constrain(logits, ("batch", "seq", "vocab"))
    return logits, aux


def loss_fn(params: Dict, batch: Dict[str, jax.Array],
            config: MoEConfig) -> Tuple[jax.Array, Dict]:
    """Next-token CE + balance/z aux losses. batch: {"tokens": (b, s+1)}."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, aux = forward(params, inputs, config)
    mask = batch.get("mask")
    ce = next_token_ce(logits, targets,
                       mask[:, 1:] if mask is not None else None)
    loss = (ce + config.balance_loss_coef * aux["balance_loss"]
            + config.z_loss_coef * aux["router_z_loss"])
    metrics = {"loss": ce, "total_loss": loss,
               "tokens": jnp.array(targets.size, jnp.float32), **aux}
    return loss, metrics
