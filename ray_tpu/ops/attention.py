"""Attention ops: XLA-fused reference + Pallas flash attention (fwd + bwd).

Design (TPU-first):
  * flash_attention is DIFFERENTIABLE (custom_vjp): the forward kernel
    also emits the per-row logsumexp; the backward recomputes attention
    blockwise in two Pallas kernels (dQ; dK/dV) — FlashAttention-2's
    schedule — so training never materializes the (b, h, s, s) logits.
  * The core returns (out, lse) so sequence-parallel callers
    (parallel/ring.py) can merge per-chunk results by logsumexp; the lse
    cotangent folds into the backward's delta term (ds = p*(dp-Δ+g_lse)).
  * mha_reference stays as the O(s^2)-memory jnp reference: XLA fuses the
    fp32 softmax into the matmuls; it is the numerics oracle in tests and
    the fallback for shapes the kernels don't tile well.
  * Serving/prefill uses the same forward kernel (no backward needed):
    online softmax over KV blocks, O(seq) memory, causal-block skipping —
    the TTFT hot path the reference outsources to vLLM's CUDA kernels.
  * GQA (n_kv_heads < n_heads): the flash kernels read K/V UNREPEATED —
    BlockSpec index maps (_kv_row) steer each q-head program at its kv
    head, and dK/dV group sums are explicit (grouped inner grid in the
    tiled pass; a post-kernel reshape-sum in the resident pass).
    mha_reference still uses logical repeat_kv with autodiff summing.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Lane width of the LSE/delta side outputs. Mosaic requires the last two
# block dims to be (8, 128)-divisible or equal to the array dims, so scalar
# per-row values are carried in a 128-lane fp32 plane (column 0 is the
# value; the rest is broadcast) exactly like the reference TPU kernel
# (jax/experimental/pallas/ops/tpu/flash_attention.py MIN_BLOCK_SIZE).
LANES = 128

# Longest padded sequence for which the backward / forward use the
# whole-sequence-resident kernels (above it, the O(block)-VMEM tiled
# kernels take over — see _flash_bwd_rule / _flash_call). The resident
# kernels skip causal-dead KV blocks entirely (no tile DMA) and are ~18%
# faster where they fit; residency grows linearly with seq and busts the
# ~16 MB scoped VMEM near 8k (bwd) / 16k (fwd). Module-level so tests can
# force the tiled paths at interpret-friendly sizes.
_BWD_RESIDENT_MAX_ROWS = 4096
_FWD_RESIDENT_MAX_ROWS = 8192


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """(batch, seq, kv_heads, hd) -> (batch, seq, kv_heads*n_rep, hd)."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array, *,
                  causal: bool = True, scale: Optional[float] = None,
                  positions_q: Optional[jax.Array] = None,
                  positions_kv: Optional[jax.Array] = None) -> jax.Array:
    """q: (b, sq, h, d); k/v: (b, skv, hkv, d). Returns (b, sq, h, d).

    fp32 softmax; XLA fuses this chain on TPU. The causal mask compares
    absolute positions when provided (needed for ring/sequence parallelism).
    """
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        k = repeat_kv(k, h // hkv)
        v = repeat_kv(v, h // hkv)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        pos_q = positions_q if positions_q is not None else jnp.arange(sq)
        pos_k = positions_kv if positions_kv is not None else jnp.arange(k.shape[1])
        mask = pos_q[:, None] >= pos_k[None, :]
        logits = jnp.where(mask[None, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# Pallas flash-attention forward (TPU)
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                      seq_kv: int, true_kv: int, causal: bool, scale: float,
                      block_q: int):
    """Grid: (batch*heads, num_q_blocks). Blocks:
    q_ref: (block_q, d), k_ref/v_ref: (seq_kv, d) resident, o_ref:
    (block_q, d), lse_ref: (block_q, LANES) — per-row logsumexp of the
    SCALED logits broadcast across lanes (column 0 is authoritative),
    consumed by the backward kernels and by ring-attention merges.

    Online softmax over KV blocks; with causal=True, KV blocks entirely above
    the diagonal are skipped (the scheduling win of flash attention).
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # block: (1, block_q, d)
    d = q.shape[-1]

    m = jnp.full((block_q, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q, 1), dtype=jnp.float32)
    acc = jnp.zeros((block_q, d), dtype=jnp.float32)

    q_start = qi * block_q
    num_k_blocks = pl.cdiv(seq_kv, block_k)
    # Causal: only iterate KV blocks whose start is <= the last query row.
    max_kb = jnp.where(
        causal, (q_start + block_q - 1) // block_k + 1, num_k_blocks)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T  # (block_q, block_k)
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if true_kv != seq_kv:  # padded tail block: mask padded keys
            s = jnp.where(k_pos < true_kv, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = alpha * acc + p @ v_blk
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, max_kb, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if lse_ref is not None:
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(jnp.maximum(l, 1e-30)),
                                      (block_q, LANES))


def _flash_fwd_kernel_nolse(q_ref, k_ref, v_ref, o_ref, **kw):
    """Forward without the LSE side output: the serving/prefill path needs
    only `out`, and the (bh, sq, LANES) fp32 lane plane would be ~128x the
    useful bytes of pure HBM write traffic on the TTFT hot path."""
    _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, None, **kw)


def _flash_fwd_kernel_tiled(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref,
                            l_ref, acc_ref, *, block_k: int,
                            num_k_blocks: int, true_kv: int, seq_kv: int,
                            causal: bool, scale: float, block_q: int):
    """Long-context forward. Grid: (batch*heads, num_q_blocks,
    num_k_blocks) — the KV walk is a grid dimension so one (block_k, d)
    tile is VMEM-resident at a time (the whole-sequence-resident kernel
    above busts the ~16 MB scoped VMEM near seq 16k). Online-softmax
    state (m, l, acc) lives in f32 scratch persisting across the inner
    grid steps; outputs are written on the last one."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, dtype=m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    live = ((k_start <= q_start + block_q - 1) if causal
            else (kb >= 0))  # traced either way for pl.when

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = q @ k_blk.T  # (block_q, block_k)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if true_kv != seq_kv:  # padded tail block: mask padded keys
            s = jnp.where(k_pos < true_kv, s, NEG_INF)
        m = m_ref[:, 0:1]
        l = l_ref[:, 0:1]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = alpha * acc_ref[...] + p @ v_blk
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == num_k_blocks - 1)
    def _write():
        m = m_ref[:, 0:1]
        l = l_ref[:, 0:1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = jnp.broadcast_to(
                m + jnp.log(jnp.maximum(l, 1e-30)), (block_q, LANES))


def _flash_fwd_kernel_tiled_nolse(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                                  acc_ref, **kw):
    _flash_fwd_kernel_tiled(q_ref, k_ref, v_ref, o_ref, None, m_ref, l_ref,
                            acc_ref, **kw)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *, block_k: int, num_k_blocks: int,
                         true_kv: int, seq_kv: int, causal: bool,
                         scale: float, block_q: int):
    """dQ pass. Grid: (batch*heads, num_q_blocks, num_k_blocks) — the KV
    walk is a GRID dimension, not an in-kernel loop, so only one
    (block_k, d) K/V tile is VMEM-resident at a time (Mosaic pipelines the
    tile DMAs) and VMEM stays O(block) at any sequence length; the old
    whole-sequence-resident layout blew the ~16 MB scoped VMEM budget at
    seq 8192. dQ accumulates in an f32 scratch that persists across the
    innermost grid steps; the out block is written once, on the last step.
    Recomputes p blockwise from (q, k, lse) — no stored logits. delta_ref
    carries rowsum(dO*O) - g_lse (the lse cotangent folds in; see
    _flash_bwd_rule)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    # Causal: KV blocks entirely above the diagonal contribute nothing —
    # compute (not the tile DMA) is skipped for them.
    live = ((k_start <= q_start + block_q - 1) if causal
            else (kb >= 0))  # traced either way for pl.when

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]    # (block_q, 1) from the lane plane
        delta = delta_ref[0][:, 0:1]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = (q @ k_blk.T) * scale
        p = jnp.exp(s - lse)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        if true_kv != seq_kv:
            p = jnp.where(k_pos < true_kv, p, 0.0)
        dp = do @ v_blk.T
        ds = p * (dp - delta)
        acc_ref[...] += ds @ k_blk

    @pl.when(kb == num_k_blocks - 1)
    def _write():
        dq_ref[0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *,
                          block_q: int, num_q_blocks: int, n_rep: int,
                          true_kv: int, mask_kv_tail: bool, causal: bool,
                          scale: float, block_k: int):
    """dK/dV pass, GQA-native. Grid: (batch*kv_heads, num_k_blocks,
    n_rep * num_q_blocks) — one program per KV head; the inner grid walks
    every (group member g, q block qi) pair with (g, qi) = divmod(inner,
    num_q_blocks), the BlockSpec index maps steering the q-side tiles to
    q-head row kvh*n_rep + g (same VMEM-bounding rationale as the dQ
    pass). dK/dV accumulate the whole group's contribution in f32 scratch
    and are written once, on the last inner step. Causal skip mirrors the
    forward: q blocks strictly above the diagonal are dead. Padded q rows
    (beyond true seq) contribute nothing even unmasked: their dO and
    delta are zero-padded, so ds == 0 and p^T @ dO adds 0."""
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    qin = pl.program_id(2)
    qi = qin % num_q_blocks
    k_start = kb * block_k
    q_start = qi * block_q
    num_inner = n_rep * num_q_blocks

    @pl.when(qin == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros(dk_acc_ref.shape, dk_acc_ref.dtype)
        dv_acc_ref[...] = jnp.zeros(dv_acc_ref.shape, dv_acc_ref.dtype)

    live = ((q_start + block_q - 1 >= k_start) if causal
            else (qin >= 0))  # traced either way for pl.when

    @pl.when(live)
    def _accumulate():
        k_blk = k_ref[0].astype(jnp.float32)   # (block_k, d)
        v_blk = v_ref[0].astype(jnp.float32)
        q_blk = q_ref[0].astype(jnp.float32)   # (block_q, d)
        do_blk = do_ref[0].astype(jnp.float32)
        lse_blk = lse_ref[0][:, 0:1]
        delta_blk = delta_ref[0][:, 0:1]
        s = (q_blk @ k_blk.T) * scale   # (block_q, block_k)
        p = jnp.exp(s - lse_blk)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        if mask_kv_tail:  # padded tail keys must not receive dK/dV
            p = jnp.where(k_pos < true_kv, p, 0.0)
        dv_acc_ref[...] += p.T @ do_blk
        dp = do_blk @ v_blk.T
        ds = p * (dp - delta_blk)
        dk_acc_ref[...] += ds.T @ q_blk

    @pl.when(qin == num_inner - 1)
    def _write():
        dk_ref[0] = (dk_acc_ref[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, seq_kv: int, true_kv: int,
                         causal: bool, scale: float, block_q: int):
    """Whole-sequence-resident dQ pass (grid (batch*heads, num_q_blocks)):
    K/V live in VMEM for the whole program, and the in-kernel fori SKIPS
    causal-dead KV blocks entirely (no tile DMA, no compute) — ~18%
    faster than the tiled variant at seq 2048, but residency grows with
    seq and busts the ~16 MB VMEM budget near 8k (the tiled kernels
    take over there; see _flash_bwd_rule). Recomputes p blockwise
    from (q, k, lse) — no stored logits. delta_ref carries
    rowsum(dO*O) - g_lse (the lse cotangent folds in here; see _flash_bwd).
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0:1]        # (block_q, 1) from the lane plane
    delta = delta_ref[0][:, 0:1]
    d = q.shape[-1]

    q_start = qi * block_q
    num_k_blocks = pl.cdiv(seq_kv, block_k)
    max_kb = jnp.where(
        causal, (q_start + block_q - 1) // block_k + 1, num_k_blocks)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = (q @ k_blk.T) * scale
        p = jnp.exp(s - lse)
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        if true_kv != seq_kv:
            p = jnp.where(k_pos < true_kv, p, 0.0)
        dp = do @ v_blk.T
        ds = p * (dp - delta)
        return dq + ds @ k_blk

    dq = jax.lax.fori_loop(0, max_kb, body,
                           jnp.zeros((block_q, d), dtype=jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, seq_q: int,
                          true_kv: int, mask_kv_tail: bool, causal: bool,
                          scale: float, block_k: int):
    """Whole-sequence-resident dK/dV pass (see the dQ twin above for the
    residency-vs-seq tradeoff). Loops over q blocks at
    or below the diagonal (causal skip mirrored from the forward). Padded q
    rows (seq_q is the PADDED length) contribute nothing without masking:
    their dO and delta are zero-padded, so ds == 0 and p^T @ dO adds 0."""
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)   # (block_k, d)
    v_blk = v_ref[0].astype(jnp.float32)
    d = k_blk.shape[-1]

    k_start = kb * block_k
    num_q_blocks = pl.cdiv(seq_q, block_q)
    # Causal: q blocks strictly above the diagonal contribute nothing.
    min_qb = jnp.where(causal, k_start // block_q, 0)

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, pl.ds(qi * block_q, block_q), :][:, 0:1]
        delta_blk = delta_ref[0, pl.ds(qi * block_q, block_q), :][:, 0:1]
        s = (q_blk @ k_blk.T) * scale   # (block_q, block_k)
        p = jnp.exp(s - lse_blk)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        if mask_kv_tail:  # padded tail keys must not receive dK/dV
            p = jnp.where(k_pos < true_kv, p, 0.0)
        dv_new = dv + p.T @ do_blk
        dp = do_blk @ v_blk.T
        ds = p * (dp - delta_blk)
        dk_new = dk + ds.T @ q_blk
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(
        min_qb, num_q_blocks, body,
        (jnp.zeros((block_k, d), dtype=jnp.float32),
         jnp.zeros((block_k, d), dtype=jnp.float32)))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _vma(*xs):
    """Union of the inputs' varying-mesh-axes sets: pallas_call out_shapes
    inside shard_map (ring attention) must declare how outputs vary
    (jax>=0.7 check_vma); outside shard_map this is the empty set."""
    out = frozenset()
    for x in xs:
        try:
            out = out | jax.typeof(x).vma
        except AttributeError:
            return None
    return out


def _sds(shape, dtype, vma):
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _fold(x):
    """(b, s, h, d) -> (b*h, s, d) for the kernels' grid layout."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _kv_row(h: int, hkv: int):
    """Index-map arithmetic for GQA: q-head grid row -> kv-head row.

    Q is folded to (b*h, s, d) rows bi*h + hi; K/V stay UNREPEATED at
    (b*hkv, s, d) rows bi*hkv + hi//n_rep. Mapping the kv head in the
    BlockSpec instead of materializing repeat_kv skips the repeated
    K/V copies entirely (2x K/V HBM traffic and residuals for the
    llama GQA configs), which is where long-context bandwidth goes."""
    n_rep = h // hkv
    return lambda bh: (bh // h) * hkv + (bh % h) // n_rep


def _flash_call(q, k, v, causal, scale, block_q, block_k, interpret,
                emit_lse: bool = True):
    """Run the forward kernel; q: (b, s, h, d), k/v: (b, s, hkv, d) with
    hkv dividing h (GQA handled natively via _kv_row index maps — no
    repeated copies). Returns (out, lse) with lse shaped (b, h, sq) in
    fp32; with emit_lse=False returns (out, None) and the kernel writes
    no LSE plane (serving hot path)."""
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    kvr = _kv_row(h, hkv)
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    vma = _vma(q, k, v)
    qt, kt, vt = _fold(q), _fold(k), _fold(v)
    # Pad sequence dims up to block multiples: in-kernel pl.ds slices CLAMP
    # at the array edge, which would silently mislabel tail rows. Padded
    # keys are masked inside the kernels (true_kv); padded q rows are
    # sliced off the outputs.
    sq_p = -(-sq // block_q) * block_q
    skv_p = -(-skv // block_k) * block_k
    if sq_p != sq:
        qt = jnp.pad(qt, ((0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        kt = jnp.pad(kt, ((0, 0), (0, skv_p - skv), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, skv_p - skv), (0, 0)))
    if skv_p <= _FWD_RESIDENT_MAX_ROWS:
        grid = (b * h, sq_p // block_q)
        kw = dict(block_k=block_k, seq_kv=skv_p, true_kv=skv, causal=causal,
                  scale=scale, block_q=block_q)
        out_specs = [pl.BlockSpec((1, block_q, d),
                                  lambda bh, qi: (bh, qi, 0))]
        out_shape = [_sds((b * h, sq_p, d), q.dtype, vma)]
        if emit_lse:
            kernel = functools.partial(_flash_fwd_kernel, **kw)
            out_specs.append(
                pl.BlockSpec((1, block_q, LANES),
                             lambda bh, qi: (bh, qi, 0)))
            out_shape.append(_sds((b * h, sq_p, LANES), jnp.float32, vma))
        else:
            kernel = functools.partial(_flash_fwd_kernel_nolse, **kw)
        res = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
                pl.BlockSpec((1, skv_p, d),
                             lambda bh, qi: (kvr(bh), 0, 0)),
                pl.BlockSpec((1, skv_p, d),
                             lambda bh, qi: (kvr(bh), 0, 0)),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(qt, kt, vt)
    else:
        # Long-context: KV walk as a grid dimension, O(block) VMEM (see
        # _flash_fwd_kernel_tiled).
        from jax.experimental.pallas import tpu as pltpu

        num_qb, num_kb = sq_p // block_q, skv_p // block_k
        kw = dict(block_k=block_k, num_k_blocks=num_kb, true_kv=skv,
                  seq_kv=skv_p, causal=causal, scale=scale, block_q=block_q)
        out_specs = [pl.BlockSpec((1, block_q, d),
                                  lambda bh, qi, kb: (bh, qi, 0))]
        out_shape = [_sds((b * h, sq_p, d), q.dtype, vma)]
        if emit_lse:
            kernel = functools.partial(_flash_fwd_kernel_tiled, **kw)
            out_specs.append(
                pl.BlockSpec((1, block_q, LANES),
                             lambda bh, qi, kb: (bh, qi, 0)))
            out_shape.append(_sds((b * h, sq_p, LANES), jnp.float32, vma))
        else:
            kernel = functools.partial(_flash_fwd_kernel_tiled_nolse, **kw)
        res = pl.pallas_call(
            kernel,
            grid=(b * h, num_qb, num_kb),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda bh, qi, kb: (bh, qi, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda bh, qi, kb: (kvr(bh), kb, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda bh, qi, kb: (kvr(bh), kb, 0)),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((block_q, LANES), jnp.float32),
                            pltpu.VMEM((block_q, LANES), jnp.float32),
                            pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=interpret,
        )(qt, kt, vt)
    out = _unfold(res[0][:, :sq], b, h)
    if not emit_lse:
        return out, None
    return out, res[1][:, :sq, 0].reshape(b, h, sq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_call(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _flash_call(q, k, v, causal, scale, block_q, block_k,
                           interpret)
    # Name the kernel's outputs so a checkpoint policy can SAVE them
    # (save_only_these_names): the flash backward needs exactly (q, k, v,
    # out, lse), and q/k/v are cheap dot recomputes from the saved layer
    # input — with out+lse saved, the rematerialized backward DCEs the
    # whole O(s^2) forward kernel instead of re-running it. That is the
    # "flash" remat policy (models/llama.py), the long-context middle
    # ground between "dots" (too much memory past 8k) and full remat
    # (recomputes the quadratic kernel).
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd_resident_calls(qt, kt, vt, dot, lse_t, delta, *, b, h, hkv,
                              d, sq, skv, sq_p, skv_p, block_q, block_k,
                              causal, scale, vma, interpret, q_dtype,
                              k_dtype, v_dtype):
    """Backward via the whole-sequence-resident kernels (small-seq fast
    path; see the implementation-choice comment in _flash_bwd_rule).

    GQA: K/V are read unrepeated via _kv_row index maps. The dK/dV pass
    still runs one program per Q head (its per-(bh, kb) scratchless
    accumulation cannot also sum across heads), so it emits per-q-head
    partials at (b*h, skv, d) and the group sum happens outside — small
    seq only, so the extra HBM is bounded."""
    from jax.experimental import pallas as pl

    kvr = _kv_row(h, hkv)
    n_rep = h // hkv
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel_resident, block_k=block_k,
                          seq_kv=skv_p, true_kv=skv, causal=causal,
                          scale=scale, block_q=block_q),
        grid=(b * h, sq_p // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, skv_p, d), lambda bh, qi: (kvr(bh), 0, 0)),
            pl.BlockSpec((1, skv_p, d), lambda bh, qi: (kvr(bh), 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=_sds((b * h, sq_p, d), q_dtype, vma),
        interpret=interpret,
    )(qt, kt, vt, dot, lse_t, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel_resident, block_q=block_q,
                          seq_q=sq_p, true_kv=skv,
                          mask_kv_tail=skv_p != skv,
                          causal=causal, scale=scale, block_k=block_k),
        grid=(b * h, skv_p // block_k),
        in_specs=[
            pl.BlockSpec((1, sq_p, d), lambda bh, kb: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb: (kvr(bh), kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb: (kvr(bh), kb, 0)),
            pl.BlockSpec((1, sq_p, d), lambda bh, kb: (bh, 0, 0)),
            pl.BlockSpec((1, sq_p, LANES), lambda bh, kb: (bh, 0, 0)),
            pl.BlockSpec((1, sq_p, LANES), lambda bh, kb: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, kb: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb: (bh, kb, 0)),
        ],
        out_shape=[
            # f32 partials ONLY when a group sum follows (n_rep > 1);
            # plain MHA writes the final dtype directly — no widened HBM
            # traffic, no extra cast pass.
            _sds((b * h, skv_p, d),
                 jnp.float32 if n_rep > 1 else k_dtype, vma),
            _sds((b * h, skv_p, d),
                 jnp.float32 if n_rep > 1 else v_dtype, vma),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse_t, delta)
    if n_rep > 1:
        # Per-q-head partials -> kv-head grads. Head order after _fold is
        # hi = kvh*n_rep + g, so adjacent rows within a group sum.
        dk = dk.reshape(b, hkv, n_rep, skv_p, d).sum(axis=2).reshape(
            b * hkv, skv_p, d).astype(k_dtype)
        dv = dv.reshape(b, hkv, n_rep, skv_p, d).sum(axis=2).reshape(
            b * hkv, skv_p, d).astype(v_dtype)
    return (_unfold(dq[:, :sq], b, h),
            _unfold(dk[:, :skv], b, hkv),
            _unfold(dv[:, :skv], b, hkv))


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, res, cts):
    from jax.experimental import pallas as pl

    q, k, v, out, lse = res
    g_out, g_lse = cts
    b, sq, h, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    kvr = _kv_row(h, hkv)
    n_rep = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    vma = _vma(q, k, v, g_out)
    qt, kt, vt = _fold(q), _fold(k), _fold(v)
    dot = _fold(g_out.astype(jnp.float32))
    ot = _fold(out.astype(jnp.float32))
    lse_t = lse.reshape(b * h, sq)
    # delta = rowsum(dO*O); an lse cotangent shifts it (d lse/d s = p, so
    # ds = p*(dp - delta + g_lse) == p*(dp - (delta - g_lse))).
    delta = jnp.sum(dot * ot, axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.reshape(b * h, sq).astype(jnp.float32)

    # Same tail-block padding as the forward (pl.ds clamps at array edges).
    # lse pads with +1e30 so padded q rows give p = exp(s - 1e30) == 0;
    # dO/delta pad with zeros, making padded rows exact no-ops.
    sq_p = -(-sq // block_q) * block_q
    skv_p = -(-skv // block_k) * block_k
    if sq_p != sq:
        pad = ((0, 0), (0, sq_p - sq))
        qt = jnp.pad(qt, pad + ((0, 0),))
        dot = jnp.pad(dot, pad + ((0, 0),))
        lse_t = jnp.pad(lse_t, pad, constant_values=1e30)
        delta = jnp.pad(delta, pad)
    if skv_p != skv:
        kt = jnp.pad(kt, ((0, 0), (0, skv_p - skv), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, skv_p - skv), (0, 0)))

    # Expand per-row scalars into the 128-lane plane the kernels read
    # (Mosaic tiling: a 2D (bh, s) array cannot be blocked (1, block_q)).
    lse_t = jnp.broadcast_to(lse_t[..., None], (b * h, sq_p, LANES))
    delta = jnp.broadcast_to(delta[..., None], (b * h, sq_p, LANES))

    from jax.experimental.pallas import tpu as pltpu

    # Two implementations of each pass (same math, same numerics):
    #   * resident — whole-sequence K/V (dQ) / q-side tensors (dK/dV) in
    #     VMEM, causal-dead blocks skipped entirely. Fastest, but VMEM
    #     residency grows linearly with seq (dK/dV pass: ~1.8 KB/row ->
    #     ~15 MB at 8k, past the ~16 MB scoped budget).
    #   * tiled — the walked axis is a grid dimension, one (block, d)
    #     tile resident at a time, f32 scratch accumulation: O(block)
    #     VMEM at ANY seq, ~18% slower at 2048 (dead blocks still DMA).
    # Pick resident while the bigger pass fits comfortably.
    resident = max(sq_p, skv_p) <= _BWD_RESIDENT_MAX_ROWS
    if resident:
        return _flash_bwd_resident_calls(
            qt, kt, vt, dot, lse_t, delta, b=b, h=h, hkv=hkv, d=d, sq=sq,
            skv=skv, sq_p=sq_p, skv_p=skv_p, block_q=block_q,
            block_k=block_k, causal=causal, scale=scale, vma=vma,
            interpret=interpret, q_dtype=q.dtype, k_dtype=k.dtype,
            v_dtype=v.dtype)

    num_qb, num_kb = sq_p // block_q, skv_p // block_k
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          num_k_blocks=num_kb, true_kv=skv, seq_kv=skv_p,
                          causal=causal, scale=scale, block_q=block_q),
        grid=(b * h, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, kb: (kvr(bh), kb, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, kb: (kvr(bh), kb, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES),
                         lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES),
                         lambda bh, qi, kb: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, kb: (bh, qi, 0)),
        out_shape=_sds((b * h, sq_p, d), q.dtype, vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse_t, delta)

    # dK/dV GQA-native: one program per KV head; the inner grid walks
    # every (group member, q block) pair — n_rep * num_qb steps — and the
    # f32 scratch accumulates the whole group's contribution before one
    # write at (b*hkv) rows. Q-side index maps decompose the inner index
    # as (g, qi) = divmod(qin, num_qb); q-head row = bkv-derived batch *
    # h + kv_head * n_rep + g (head order after _fold is kvh*n_rep + g).
    def _q_row(bkv, qin):
        return ((bkv // hkv) * h + (bkv % hkv) * n_rep + qin // num_qb)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          num_q_blocks=num_qb, n_rep=n_rep, true_kv=skv,
                          mask_kv_tail=skv_p != skv, causal=causal,
                          scale=scale, block_k=block_k),
        grid=(b * hkv, num_kb, n_rep * num_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bkv, kb, qin: (_q_row(bkv, qin),
                                               qin % num_qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, kb, qin: (bkv, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, kb, qin: (bkv, kb, 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bkv, kb, qin: (_q_row(bkv, qin),
                                               qin % num_qb, 0)),
            pl.BlockSpec((1, block_q, LANES),
                         lambda bkv, kb, qin: (_q_row(bkv, qin),
                                               qin % num_qb, 0)),
            pl.BlockSpec((1, block_q, LANES),
                         lambda bkv, kb, qin: (_q_row(bkv, qin),
                                               qin % num_qb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bkv, kb, qin: (bkv, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, kb, qin: (bkv, kb, 0)),
        ],
        out_shape=[
            _sds((b * hkv, skv_p, d), k.dtype, vma),
            _sds((b * hkv, skv_p, d), v.dtype, vma),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse_t, delta)

    return (_unfold(dq[:, :sq], b, h), _unfold(dk[:, :skv], b, hkv),
            _unfold(dv[:, :skv], b, hkv))


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _flash_prep(q, k, v, scale, interpret):
    """Shared defaults for the flash entry points. K/V stay at their
    native kv-head count — the kernels map kv heads via _kv_row index
    arithmetic instead of materializing repeat_kv."""
    h, hkv = q.shape[2], k.shape[2]
    if h % hkv != 0:
        raise ValueError(f"n_heads {h} not divisible by n_kv_heads {hkv}")
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        from ray_tpu.ops import is_tpu_backend

        interpret = not is_tpu_backend()
    return k, v, scale, interpret


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None,
                    return_lse: bool = False):
    """Differentiable Pallas flash attention (fwd + custom_vjp bwd).
    q: (b, sq, h, d), k/v: (b, skv, hkv, d). With return_lse=True also
    returns the (b, h, sq) logsumexp (for sequence-parallel merges)."""
    k, v, scale, interpret = _flash_prep(q, k, v, scale, interpret)
    out, lse = _flash(q, k, v, causal, scale, block_q, block_k, interpret)
    return (out, lse) if return_lse else out


def flash_attention_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True, scale: Optional[float] = None,
                        block_q: int = 512, block_k: int = 512,
                        interpret: Optional[bool] = None) -> jax.Array:
    """Forward-only entry point (serving hot path; no residual outputs)."""
    k, v, scale, interpret = _flash_prep(q, k, v, scale, interpret)
    out, _ = _flash_call(q, k, v, causal, scale, block_q, block_k, interpret,
                         emit_lse=False)
    return out


def attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None,
              impl: str = "auto") -> jax.Array:
    """Dispatch: "reference" (XLA-fused jnp), "flash" (Pallas fwd+bwd —
    O(seq) memory, differentiable). "auto" picks flash on TPU when the
    head dim tiles the MXU lane width, else the fused reference."""
    if impl == "auto":
        from ray_tpu.ops import is_tpu_backend

        d = q.shape[-1]
        impl = ("flash" if is_tpu_backend() and d % 128 == 0
                and q.shape[1] >= 256 else "reference")
    if impl == "reference":
        return mha_reference(q, k, v, causal=causal, scale=scale)
    if impl == "flash":
        return flash_attention(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")
