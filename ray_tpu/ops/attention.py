"""Attention ops: XLA-fused reference + Pallas flash-attention forward.

Design (TPU-first):
  * Training uses the jnp reference: XLA on TPU fuses the fp32 softmax into
    the two matmuls and handles the backward pass; at training block sizes
    this keeps the MXU busy without hand-scheduling.
  * Serving/prefill uses the Pallas flash kernel (no backward needed): online
    softmax over KV blocks, O(seq) memory, causal-block skipping. This is the
    TTFT hot path the reference outsources to vLLM's CUDA kernels.
  * GQA (n_kv_heads < n_heads) supported everywhere by logical repeat.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """(batch, seq, kv_heads, hd) -> (batch, seq, kv_heads*n_rep, hd)."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array, *,
                  causal: bool = True, scale: Optional[float] = None,
                  positions_q: Optional[jax.Array] = None,
                  positions_kv: Optional[jax.Array] = None) -> jax.Array:
    """q: (b, sq, h, d); k/v: (b, skv, hkv, d). Returns (b, sq, h, d).

    fp32 softmax; XLA fuses this chain on TPU. The causal mask compares
    absolute positions when provided (needed for ring/sequence parallelism).
    """
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        k = repeat_kv(k, h // hkv)
        v = repeat_kv(v, h // hkv)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        pos_q = positions_q if positions_q is not None else jnp.arange(sq)
        pos_k = positions_kv if positions_kv is not None else jnp.arange(k.shape[1])
        mask = pos_q[:, None] >= pos_k[None, :]
        logits = jnp.where(mask[None, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# Pallas flash-attention forward (TPU)
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_kv: int,
                      causal: bool, scale: float, block_q: int):
    """Grid: (batch*heads, num_q_blocks). Blocks:
    q_ref: (block_q, d), k_ref/v_ref: (seq_kv, d) resident, o_ref: (block_q, d).

    Online softmax over KV blocks; with causal=True, KV blocks entirely above
    the diagonal are skipped (the scheduling win of flash attention).
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # block: (1, block_q, d)
    d = q.shape[-1]

    m = jnp.full((block_q, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q, 1), dtype=jnp.float32)
    acc = jnp.zeros((block_q, d), dtype=jnp.float32)

    q_start = qi * block_q
    num_k_blocks = pl.cdiv(seq_kv, block_k)
    # Causal: only iterate KV blocks whose start is <= the last query row.
    max_kb = jnp.where(
        causal, (q_start + block_q - 1) // block_k + 1, num_k_blocks)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T  # (block_q, block_k)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = alpha * acc + p @ v_blk
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, max_kb, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True, scale: Optional[float] = None,
                        block_q: int = 256, block_k: int = 256,
                        interpret: Optional[bool] = None) -> jax.Array:
    """Pallas flash forward. q: (b, sq, h, d), k/v: (b, skv, hkv, d)."""
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    if hkv != h:
        k = repeat_kv(k, h // hkv)
        v = repeat_kv(v, h // hkv)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    if interpret is None:
        from ray_tpu.ops import is_tpu_backend

        interpret = not is_tpu_backend()

    # Layout: fold (b, h) into the grid's first axis; operate on (seq, d).
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, skv, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, skv, d)

    grid = (b * h, pl.cdiv(sq, block_q))
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, seq_kv=skv, causal=causal,
        scale=scale, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, skv, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, skv, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None,
              impl: str = "auto") -> jax.Array:
    """Dispatch: "reference" (training, XLA-fused, differentiable) or
    "flash" (serving forward)."""
    if impl == "auto":
        impl = "reference"
    if impl == "reference":
        return mha_reference(q, k, v, causal=causal, scale=scale)
    if impl == "flash":
        return flash_attention_fwd(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")
