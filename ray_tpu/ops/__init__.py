"""TPU compute ops (attention, layers, paged attention).

Backend detection lives here: TPU chips can surface under jax platform
names other than "tpu" — notably "axon", a PJRT plugin that proxies a
remote TPU and aliases the Pallas "tpu" lowering rules — so every
"am I on real TPU hardware?" decision (e.g. Pallas interpret mode) must
go through :func:`is_tpu_backend`, never a raw
``jax.default_backend() == "tpu"`` comparison.
"""

from __future__ import annotations

TPU_PLATFORMS = ("tpu", "axon")


def is_tpu_backend() -> bool:
    """True when jax's default backend executes on TPU hardware (native
    libtpu or a proxying PJRT plugin with TPU lowering rules)."""
    import jax

    backend = jax.default_backend()
    if backend in TPU_PLATFORMS:
        return True
    try:
        return "tpu" in jax.devices()[0].device_kind.lower()
    except Exception:
        return False
