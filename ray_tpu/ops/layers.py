"""Elementwise / normalization / rotary ops.

Plain jnp: XLA fuses these into surrounding matmuls on TPU; dedicated pallas
kernels only pay off for the attention inner loop (see ops/attention.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 500000.0):
    """Precompute RoPE cos/sin tables: (max_seq, head_dim//2), float32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array | None = None) -> jax.Array:
    """x: (..., seq, heads, head_dim). cos/sin: (max_seq, head_dim//2).
    positions: (..., seq) absolute positions; default arange."""
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq]
        s = sin[:seq]
        c = c[None, :, None, :]
        s = s[None, :, None, :]
    else:
        c = cos[positions][..., :, None, :]
        s = sin[positions][..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def swiglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    return jax.nn.silu(gate) * up
