"""Ragged paged attention: the serving-decode hot op.

Reference analog: the paged-attention CUDA kernels inside vLLM, which the
reference repo only places (python/ray/llm/_internal/serve/deployments/llm/
vllm/vllm_engine.py:222). TPU-native design: one kernel serves BOTH decode
(one query token per sequence) and chunked prefill (a block of query tokens
per sequence) — "ragged" means each sequence in the batch has its own query
count and context length; shapes stay static (bucketed) and per-sequence
lengths arrive as scalar-prefetch operands.

Layouts:
  q:            (S, Bq, H, hd)  — Bq = query tokens per sequence this step
                                  (1 for decode, chunk size for prefill)
  k/v pages:    (K, P, ps, hd)  — per-layer paged KV pool, K = kv heads
  block_tables: (S, max_pages)  int32, logical page i of seq s -> pool page
  kv_lens:      (S,) int32      — context length INCLUDING this step's tokens
  q_positions:  (S,) int32      — absolute position of q[s, 0]

The Pallas kernel walks only ceil(kv_len/ps) real pages per sequence
(double-buffered HBM->VMEM DMA), so decode cost is O(actual context), not
O(max context) — the property the round-1 jnp gather lacked.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ragged_paged_attention_reference(
        q, k_pages, v_pages, block_tables, kv_lens, q_positions, *,
        scale: Optional[float] = None):
    """jnp reference (CPU tests + fallback). Gathers the full padded context;
    the Pallas kernel below is the O(actual-context) implementation."""
    S, Bq, H, hd = q.shape
    K, P, ps, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    max_ctx = max_pages * ps
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    # (K, S, max_pages, ps, hd) -> (S, max_ctx, K, hd)
    k = k_pages[:, block_tables].transpose(1, 2, 3, 0, 4).reshape(
        S, max_ctx, K, hd)
    v = v_pages[:, block_tables].transpose(1, 2, 3, 0, 4).reshape(
        S, max_ctx, K, hd)
    if K != H:
        rep = H // K
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("sqhd,skhd->shqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(max_ctx)[None, None, None, :]
    q_abs = (q_positions[:, None] + jnp.arange(Bq)[None, :])[:, None, :, None]
    mask = (k_pos < kv_lens[:, None, None, None]) & (q_abs >= k_pos)
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("shqk,skhd->sqhd", probs, v)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _rpa_kernel(block_tables_ref, kv_lens_ref, q_pos_ref,   # scalar prefetch
                q_ref, kpages_hbm, vpages_hbm,              # tensor inputs
                o_ref,                                      # output
                k_scr, v_scr, sems,                         # scratch
                *, ps: int, scale: float, Bq: int, G: int, hd: int,
                max_pages: int):
    """Grid: (S, K). Block q_ref/o_ref: (1, 1, Bq*G, hd) — the query rows of
    kv-head `kh` for sequence `s`. KV pages stay in HBM; each page is
    double-buffer DMA'd into VMEM and folded into an online softmax."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = pl.program_id(0)
    kh = pl.program_id(1)
    kv_len = kv_lens_ref[s]
    q_pos = q_pos_ref[s]
    n_pages = pl.cdiv(kv_len, ps)

    q = q_ref[0, 0].astype(jnp.float32) * scale          # (Bq*G, hd)
    rows = Bq * G
    # Absolute position of each query row (row r belongs to query r // G).
    q_abs = q_pos + jax.lax.broadcasted_iota(jnp.int32, (rows, ps), 0) // G

    def page_dma(slot, i):
        page = block_tables_ref[s, i]
        return (pltpu.make_async_copy(kpages_hbm.at[kh, page], k_scr.at[slot],
                                      sems.at[slot, 0]),
                pltpu.make_async_copy(vpages_hbm.at[kh, page], v_scr.at[slot],
                                      sems.at[slot, 1]))

    @pl.when(n_pages > 0)
    def _():
        # Padding sequences (kv_len == 0) must not start a DMA that the
        # zero-iteration loop below would never wait on.
        kd, vd = page_dma(0, 0)
        kd.start()
        vd.start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            nk, nv = page_dma(1 - slot, i + 1)
            nk.start()
            nv.start()

        kw, vw = page_dma(slot, i)
        kw.wait()
        vw.wait()
        k_page = k_scr[slot].astype(jnp.float32)          # (ps, hd)
        v_page = v_scr[slot].astype(jnp.float32)
        sc = q @ k_page.T                                 # (rows, ps)
        k_pos = i * ps + jax.lax.broadcasted_iota(jnp.int32, (rows, ps), 1)
        valid = (k_pos < kv_len) & (q_abs >= k_pos)
        sc = jnp.where(valid, sc, NEG_INF)
        m_new = jnp.maximum(m, sc.max(axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = alpha * acc + p @ v_page
        return m_new, l_new, acc_new

    m0 = jnp.full((rows, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((rows, 1), dtype=jnp.float32)
    a0 = jnp.zeros((rows, hd), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, a0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, kv_lens,
                           q_positions, *, scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Pallas ragged paged attention (see module docstring for layouts)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, Bq, H, hd = q.shape
    K, P, ps, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    G = H // K
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    if interpret is None:
        from ray_tpu.ops import is_tpu_backend

        interpret = not is_tpu_backend()

    # (S, Bq, H, hd) -> (S, K, Bq*G, hd): rows of one kv head contiguous.
    qt = q.reshape(S, Bq, K, G, hd).transpose(0, 2, 1, 3, 4).reshape(
        S, K, Bq * G, hd)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, K),
        in_specs=[
            pl.BlockSpec((1, 1, Bq * G, hd), lambda s, kh, *_: (s, kh, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # k pages stay in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # v pages stay in HBM
        ],
        out_specs=pl.BlockSpec((1, 1, Bq * G, hd),
                               lambda s, kh, *_: (s, kh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, ps, hd), k_pages.dtype),
            pltpu.VMEM((2, ps, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _rpa_kernel, ps=ps, scale=scale, Bq=Bq, G=G, hd=hd,
        max_pages=max_pages)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, K, Bq * G, hd), q.dtype),
        interpret=interpret,
    )(block_tables, kv_lens, q_positions, qt, k_pages, v_pages)
    return out.reshape(S, K, Bq, G, hd).transpose(0, 2, 1, 3, 4).reshape(
        S, Bq, H, hd)
