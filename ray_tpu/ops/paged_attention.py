"""Ragged paged attention: the serving-decode hot op.

Reference analog: the paged-attention CUDA kernels inside vLLM, which the
reference repo only places (python/ray/llm/_internal/serve/deployments/llm/
vllm/vllm_engine.py:222). TPU-native design: one kernel serves BOTH decode
(one query token per sequence) and chunked prefill (a block of query tokens
per sequence) — "ragged" means each sequence in the batch has its own query
count and context length; shapes stay static (bucketed) and per-sequence
lengths arrive as scalar-prefetch operands.

Layouts:
  q:            (S, Bq, H, hd)  — Bq = query tokens per sequence this step
                                  (1 for decode, chunk size for prefill)
  k/v pages:    (K, P, ps, hd)  — per-layer paged KV pool, K = kv heads
  block_tables: (S, max_pages)  int32, logical page i of seq s -> pool page
  kv_lens:      (S,) int32      — context length INCLUDING this step's tokens
  q_positions:  (S,) int32      — absolute position of q[s, 0]

The Pallas kernel walks only ceil(kv_len/ps) real pages per sequence
(double-buffered HBM->VMEM DMA), so decode cost is O(actual context), not
O(max context) — the property the round-1 jnp gather lacked.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ragged_paged_attention_reference(
        q, k_pages, v_pages, block_tables, kv_lens, q_positions, *,
        scale: Optional[float] = None):
    """jnp reference (CPU tests + fallback). Gathers the full padded context;
    the Pallas kernel below is the O(actual-context) implementation."""
    S, Bq, H, hd = q.shape
    K, P, ps, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    max_ctx = max_pages * ps
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    # (K, S, max_pages, ps, hd) -> (S, max_ctx, K, hd)
    k = k_pages[:, block_tables].transpose(1, 2, 3, 0, 4).reshape(
        S, max_ctx, K, hd)
    v = v_pages[:, block_tables].transpose(1, 2, 3, 0, 4).reshape(
        S, max_ctx, K, hd)
    if K != H:
        rep = H // K
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("sqhd,skhd->shqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(max_ctx)[None, None, None, :]
    q_abs = (q_positions[:, None] + jnp.arange(Bq)[None, :])[:, None, :, None]
    mask = (k_pos < kv_lens[:, None, None, None]) & (q_abs >= k_pos)
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("shqk,skhd->sqhd", probs, v)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _rpa_kernel(block_tables_ref, kv_lens_ref, q_pos_ref,   # scalar prefetch
                q_ref, kpages_hbm, vpages_hbm,              # tensor inputs
                o_ref,                                      # output
                k_scr, v_scr, sems,                         # scratch
                *, ps: int, scale: float, Bq: int, G: int, hd: int,
                max_pages: int):
    """Grid: (S, K). Block q_ref/o_ref: (1, 1, Bq*G, hd) — the query rows of
    kv-head `kh` for sequence `s`. KV pages stay in HBM; each page is
    double-buffer DMA'd into VMEM and folded into an online softmax."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = pl.program_id(0)
    kh = pl.program_id(1)
    kv_len = kv_lens_ref[s]
    q_pos = q_pos_ref[s]
    n_pages = pl.cdiv(kv_len, ps)

    q = q_ref[0, 0].astype(jnp.float32) * scale          # (Bq*G, hd)
    rows = Bq * G
    # Absolute position of each query row (row r belongs to query r // G).
    q_abs = q_pos + jax.lax.broadcasted_iota(jnp.int32, (rows, ps), 0) // G

    def page_dma(slot, i):
        page = block_tables_ref[s, i]
        return (pltpu.make_async_copy(kpages_hbm.at[kh, page], k_scr.at[slot],
                                      sems.at[slot, 0]),
                pltpu.make_async_copy(vpages_hbm.at[kh, page], v_scr.at[slot],
                                      sems.at[slot, 1]))

    @pl.when(n_pages > 0)
    def _():
        # Padding sequences (kv_len == 0) must not start a DMA that the
        # zero-iteration loop below would never wait on.
        kd, vd = page_dma(0, 0)
        kd.start()
        vd.start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            nk, nv = page_dma(1 - slot, i + 1)
            nk.start()
            nv.start()

        kw, vw = page_dma(slot, i)
        kw.wait()
        vw.wait()
        k_page = k_scr[slot].astype(jnp.float32)          # (ps, hd)
        v_page = v_scr[slot].astype(jnp.float32)
        sc = q @ k_page.T                                 # (rows, ps)
        k_pos = i * ps + jax.lax.broadcasted_iota(jnp.int32, (rows, ps), 1)
        valid = (k_pos < kv_len) & (q_abs >= k_pos)
        sc = jnp.where(valid, sc, NEG_INF)
        m_new = jnp.maximum(m, sc.max(axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = alpha * acc + p @ v_page
        return m_new, l_new, acc_new

    m0 = jnp.full((rows, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((rows, 1), dtype=jnp.float32)
    a0 = jnp.zeros((rows, hd), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, a0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def token_seq_ids(cu_q_lens, T: int, S: int):
    """Sequence id per flat token (count of cu boundaries at or below it),
    clamped into [0, S-1] so padding tokens index real scalar rows; the
    caller masks them out separately (tok >= cu_q_lens[S])."""
    tok = jnp.arange(T)
    seq = jnp.sum(tok[:, None] >= cu_q_lens[None, 1:], axis=1).astype(
        jnp.int32)
    return jnp.minimum(seq, S - 1)


def ragged_paged_attention_unified_reference(
        q, k_pages, v_pages, block_tables, kv_lens, q_positions, cu_q_lens,
        *, scale: Optional[float] = None):
    """Token-major unified reference: q is flat (T, H, hd), sequences own
    contiguous row spans delimited by cu_q_lens (S+1 cumulative starts).

    Implemented by scattering the flat rows back into the rectangular
    (S, T, H, hd) layout and calling ragged_paged_attention_reference —
    per-row math is THE SAME FUNCTION, so a unified mixed launch is
    bit-identical to the split rectangular launches it replaces (the CPU-CI
    anchor for the engine's unified-vs-split-tick identity tests)."""
    T, H, hd = q.shape
    S = kv_lens.shape[0]
    seq = token_seq_ids(cu_q_lens, T, S)
    local = jnp.arange(T) - cu_q_lens[seq]
    valid = jnp.arange(T) < cu_q_lens[S]
    # Padding tokens scatter to column T (out of bounds -> dropped): never
    # a wrapped negative index, which would silently overwrite real rows.
    qr = jnp.zeros((S, T, H, hd), q.dtype).at[
        seq, jnp.where(valid, local, T)].set(q, mode="drop")
    out_r = ragged_paged_attention_reference(
        qr, k_pages, v_pages, block_tables, kv_lens, q_positions,
        scale=scale)
    out = out_r[seq, jnp.minimum(local, T - 1)]
    return jnp.where(valid[:, None, None], out, jnp.zeros_like(out))


def _rua_kernel(block_tables_ref, kv_lens_ref, q_pos_ref, cu_ref,  # prefetch
                q_ref, kpages_hbm, vpages_hbm,                     # tensors
                o_ref,                                             # output
                k_scr, v_scr, sems,                                # scratch
                *, ps: int, scale: float, TB: int, G: int, hd: int, S: int):
    """Grid: (T // TB, K). Block q_ref/o_ref: (1, TB, G, hd) — TB flat
    query tokens for kv head `kh`; a block may span several sequences, so
    rows carry their own sequence id (derived from the prefetched
    cu_q_lens) and every page contribution is masked per row. KV pages
    stay in HBM; each sequence in the block walks only its own
    ceil(kv_len/ps) pages, double-buffer DMA'd into VMEM and folded into
    an online softmax."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    blk = pl.program_id(0)
    kh = pl.program_id(1)
    rows = TB * G
    q = q_ref[0].astype(jnp.float32).reshape(rows, hd) * scale

    # Global token index per row (row r belongs to token r // G).
    tok = blk * TB + jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // G
    n_real = cu_ref[S]
    row_valid = tok < n_real

    def count_seq(s, acc):
        return acc + (tok >= cu_ref[s]).astype(jnp.int32)

    seq = jax.lax.fori_loop(
        1, S + 1, count_seq, jnp.zeros((rows, 1), jnp.int32))
    seq = jnp.minimum(seq, S - 1)

    def seq_of(t):
        def cnt(s, acc):
            return acc + jnp.where(t >= cu_ref[s], 1, 0)

        return jnp.minimum(jax.lax.fori_loop(1, S + 1, cnt, 0), S - 1)

    s_lo = seq_of(blk * TB)
    s_hi = seq_of(jnp.minimum(blk * TB + TB - 1, jnp.maximum(n_real - 1, 0)))

    def seq_body(s, carry):
        m, l, acc = carry
        kv_len = kv_lens_ref[s]
        n_pages = pl.cdiv(kv_len, ps)
        mine = (seq == s) & row_valid                       # (rows, 1)
        q_abs = q_pos_ref[s] + (tok - cu_ref[s])            # (rows, 1)

        def page_dma(slot, i):
            page = block_tables_ref[s, i]
            return (pltpu.make_async_copy(kpages_hbm.at[kh, page],
                                          k_scr.at[slot], sems.at[slot, 0]),
                    pltpu.make_async_copy(vpages_hbm.at[kh, page],
                                          v_scr.at[slot], sems.at[slot, 1]))

        @pl.when(n_pages > 0)
        def _():
            kd, vd = page_dma(0, 0)
            kd.start()
            vd.start()

        def body(i, carry):
            m, l, acc = carry
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 < n_pages)
            def _():
                nk, nv = page_dma(1 - slot, i + 1)
                nk.start()
                nv.start()

            kw, vw = page_dma(slot, i)
            kw.wait()
            vw.wait()
            k_page = k_scr[slot].astype(jnp.float32)        # (ps, hd)
            v_page = v_scr[slot].astype(jnp.float32)
            sc = q @ k_page.T                               # (rows, ps)
            k_pos = i * ps + jax.lax.broadcasted_iota(
                jnp.int32, (rows, ps), 1)
            ok = mine & (k_pos < kv_len) & (q_abs >= k_pos)
            sc = jnp.where(ok, sc, NEG_INF)
            m_new = jnp.maximum(m, sc.max(axis=-1, keepdims=True))
            # Explicit zero where masked: rows of OTHER sequences see an
            # all-NEG_INF page, and exp(NEG_INF - NEG_INF) == 1 would leak
            # phantom mass into their (still-empty) softmax state.
            p = jnp.where(ok, jnp.exp(sc - m_new), 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + p.sum(axis=-1, keepdims=True)
            acc_new = alpha * acc + p @ v_page
            return m_new, l_new, acc_new

        return jax.lax.fori_loop(0, n_pages, body, (m, l, acc))

    m0 = jnp.full((rows, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((rows, 1), dtype=jnp.float32)
    a0 = jnp.zeros((rows, hd), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(s_lo, s_hi + 1, seq_body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0] = out.reshape(TB, G, hd).astype(o_ref.dtype)


def ragged_paged_attention_unified(q, k_pages, v_pages, block_tables,
                                   kv_lens, q_positions, cu_q_lens, *,
                                   scale: Optional[float] = None,
                                   q_block: int = 8,
                                   interpret: Optional[bool] = None):
    """Pallas unified ragged paged attention: ONE launch for a mixed batch
    where each sequence contributes its own query-token count (decode = 1,
    spec verify = k+1, prefill chunk = up to chunk tokens).

    Layouts (vs the rectangular entry above):
      q:         (T, H, hd) flat token-major; sequence s owns rows
                 [cu_q_lens[s], cu_q_lens[s+1]); rows past cu_q_lens[S]
                 are padding
      cu_q_lens: (S+1,) int32 cumulative query starts
      block_tables/kv_lens/q_positions: per-sequence, as the rectangular
                 entry (q_positions[s] = absolute position of the FIRST
                 query token of s)

    T must be a multiple of q_block (the engine pads to token-budget
    buckets, all multiples of 8)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, H, hd = q.shape
    K, P, ps, _ = k_pages.shape
    S = kv_lens.shape[0]
    G = H // K
    TB = q_block
    if T % TB:
        raise ValueError(f"T={T} not a multiple of q_block={TB}")
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    if interpret is None:
        from ray_tpu.ops import is_tpu_backend

        interpret = not is_tpu_backend()

    # (T, H, hd) -> (K, T, G, hd): one kv head's query rows contiguous.
    qt = q.reshape(T, K, G, hd).transpose(1, 0, 2, 3)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(T // TB, K),
        in_specs=[
            pl.BlockSpec((1, TB, G, hd), lambda blk, kh, *_: (kh, blk, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # k pages stay in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # v pages stay in HBM
        ],
        out_specs=pl.BlockSpec((1, TB, G, hd),
                               lambda blk, kh, *_: (kh, blk, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, ps, hd), k_pages.dtype),
            pltpu.VMEM((2, ps, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _rua_kernel, ps=ps, scale=scale, TB=TB, G=G, hd=hd, S=S)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((K, T, G, hd), q.dtype),
        interpret=interpret,
    )(block_tables, kv_lens, q_positions, cu_q_lens, qt, k_pages, v_pages)
    return out.transpose(1, 0, 2, 3).reshape(T, H, hd)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, kv_lens,
                           q_positions, *, scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Pallas ragged paged attention (see module docstring for layouts)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, Bq, H, hd = q.shape
    K, P, ps, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    G = H // K
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    if interpret is None:
        from ray_tpu.ops import is_tpu_backend

        interpret = not is_tpu_backend()

    # (S, Bq, H, hd) -> (S, K, Bq*G, hd): rows of one kv head contiguous.
    qt = q.reshape(S, Bq, K, G, hd).transpose(0, 2, 1, 3, 4).reshape(
        S, K, Bq * G, hd)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, K),
        in_specs=[
            pl.BlockSpec((1, 1, Bq * G, hd), lambda s, kh, *_: (s, kh, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # k pages stay in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # v pages stay in HBM
        ],
        out_specs=pl.BlockSpec((1, 1, Bq * G, hd),
                               lambda s, kh, *_: (s, kh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, ps, hd), k_pages.dtype),
            pltpu.VMEM((2, ps, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _rpa_kernel, ps=ps, scale=scale, Bq=Bq, G=G, hd=hd,
        max_pages=max_pages)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, K, Bq * G, hd), q.dtype),
        interpret=interpret,
    )(block_tables, kv_lens, q_positions, qt, k_pages, v_pages)
    return out.reshape(S, K, Bq, G, hd).transpose(0, 2, 1, 3, 4).reshape(
        S, Bq, H, hd)
