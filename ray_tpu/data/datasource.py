"""Datasources: read tasks that produce blocks.

Reference analog: python/ray/data/read_api.py + datasource/ connectors. Each
datasource splits into `ReadTask`s (callables returning one block) so reads
parallelize as ordinary tasks.
"""

from __future__ import annotations

import glob as glob_mod
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import Block, block_from_batch, block_from_rows

ReadTask = Callable[[], Block]


class Datasource:
    def read_tasks(self, parallelism: int, limit: Optional[int]) -> List[ReadTask]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    def __init__(self, n: int, column: str = "id"):
        self.n = n
        self.column = column

    def read_tasks(self, parallelism, limit):
        n = self.n if limit is None else min(self.n, limit)
        parallelism = max(1, min(parallelism, n))
        per = (n + parallelism - 1) // parallelism
        tasks = []
        for i in range(parallelism):
            lo, hi = i * per, min((i + 1) * per, n)
            if lo >= hi:
                break
            col = self.column
            tasks.append(lambda lo=lo, hi=hi: block_from_batch(
                {col: np.arange(lo, hi)}))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = items

    def read_tasks(self, parallelism, limit):
        items = self.items if limit is None else self.items[:limit]
        parallelism = max(1, min(parallelism, len(items) or 1))
        per = (len(items) + parallelism - 1) // parallelism
        tasks = []
        for i in range(parallelism):
            chunk = items[i * per:(i + 1) * per]
            if not chunk:
                break
            if chunk and isinstance(chunk[0], dict):
                tasks.append(lambda c=chunk: block_from_rows(c))
            else:
                tasks.append(lambda c=chunk: block_from_batch(
                    {"item": np.asarray(c)}))
        return tasks


class NumpyDatasource(Datasource):
    def __init__(self, arrays: Dict[str, np.ndarray]):
        self.arrays = arrays

    def read_tasks(self, parallelism, limit):
        n = len(next(iter(self.arrays.values())))
        if limit is not None:
            n = min(n, limit)
        parallelism = max(1, min(parallelism, n))
        per = (n + parallelism - 1) // parallelism
        tasks = []
        for i in range(parallelism):
            lo, hi = i * per, min((i + 1) * per, n)
            if lo >= hi:
                break
            tasks.append(lambda lo=lo, hi=hi: block_from_batch(
                {k: v[lo:hi] for k, v in self.arrays.items()}))
        return tasks


class _FileDatasource(Datasource):
    def __init__(self, paths):
        if isinstance(paths, str):
            paths = [paths]
        expanded: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                expanded.extend(sorted(
                    os.path.join(p, f) for f in os.listdir(p)))
            elif any(ch in p for ch in "*?["):
                expanded.extend(sorted(glob_mod.glob(p)))
            else:
                expanded.append(p)
        if not expanded:
            raise FileNotFoundError(f"no files match {paths}")
        self.paths = expanded

    def _read_file(self, path: str) -> Block:
        raise NotImplementedError

    def read_tasks(self, parallelism, limit):
        return [lambda p=p: self._read_file(p) for p in self.paths]


class ParquetDatasource(_FileDatasource):
    def _read_file(self, path):
        import pyarrow.parquet as pq

        return pq.read_table(path)


class CSVDatasource(_FileDatasource):
    def _read_file(self, path):
        from pyarrow import csv as pacsv

        return pacsv.read_csv(path)


class JSONDatasource(_FileDatasource):
    def _read_file(self, path):
        from pyarrow import json as pajson

        return pajson.read_json(path)


# ---- write path (per-block writers used by Dataset.write_*) --------------

def write_parquet_block(block, path: str, index: int) -> str:
    import os

    import pyarrow.parquet as pq

    out = os.path.join(path, f"part-{index:05d}.parquet")
    pq.write_table(block, out)
    return out


def write_csv_block(block, path: str, index: int) -> str:
    import os

    import pyarrow.csv as pacsv

    out = os.path.join(path, f"part-{index:05d}.csv")
    pacsv.write_csv(block, out)
    return out


def write_json_block(block, path: str, index: int) -> str:
    import json
    import os

    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.json")
    with open(out, "w") as f:
        for row in BlockAccessor(block).to_rows():
            f.write(json.dumps({k: v.item() if hasattr(v, "item") else v
                                for k, v in row.items()}) + "\n")
    return out
