"""Datasources: read tasks that produce blocks.

Reference analog: python/ray/data/read_api.py + datasource/ connectors. Each
datasource splits into `ReadTask`s (callables returning one block) so reads
parallelize as ordinary tasks.
"""

from __future__ import annotations

import glob as glob_mod
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import Block, block_from_batch, block_from_rows

ReadTask = Callable[[], Block]


def _partition(n: int, parallelism: int) -> List[tuple]:
    """Ceil-divide [0, n) into at most `parallelism` contiguous (lo, hi)
    ranges (shared by every range-partitioned datasource)."""
    parallelism = max(1, min(parallelism, n or 1))
    per = (n + parallelism - 1) // parallelism
    out = []
    for i in range(parallelism):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi:
            break
        out.append((lo, hi))
    return out


class Datasource:
    def read_tasks(self, parallelism: int, limit: Optional[int]) -> List[ReadTask]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    def __init__(self, n: int, column: str = "id"):
        self.n = n
        self.column = column

    def read_tasks(self, parallelism, limit):
        n = self.n if limit is None else min(self.n, limit)
        col = self.column
        return [lambda lo=lo, hi=hi: block_from_batch({col: np.arange(lo, hi)})
                for lo, hi in _partition(n, parallelism)]


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = items

    def read_tasks(self, parallelism, limit):
        items = self.items if limit is None else self.items[:limit]
        tasks = []
        for lo, hi in _partition(len(items), parallelism):
            chunk = items[lo:hi]
            if isinstance(chunk[0], dict):
                tasks.append(lambda c=chunk: block_from_rows(c))
            else:
                tasks.append(lambda c=chunk: block_from_batch(
                    {"item": np.asarray(c)}))
        return tasks


class NumpyDatasource(Datasource):
    def __init__(self, arrays: Dict[str, np.ndarray]):
        self.arrays = arrays

    def read_tasks(self, parallelism, limit):
        n = len(next(iter(self.arrays.values())))
        if limit is not None:
            n = min(n, limit)
        return [lambda lo=lo, hi=hi: block_from_batch(
                    {k: v[lo:hi] for k, v in self.arrays.items()})
                for lo, hi in _partition(n, parallelism)]


class _FileDatasource(Datasource):
    def __init__(self, paths):
        if isinstance(paths, str):
            paths = [paths]
        expanded: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                expanded.extend(sorted(
                    os.path.join(p, f) for f in os.listdir(p)))
            elif any(ch in p for ch in "*?["):
                expanded.extend(sorted(glob_mod.glob(p)))
            else:
                expanded.append(p)
        if not expanded:
            raise FileNotFoundError(f"no files match {paths}")
        self.paths = expanded

    def _read_file(self, path: str) -> Block:
        raise NotImplementedError

    def read_tasks(self, parallelism, limit):
        return [lambda p=p: self._read_file(p) for p in self.paths]


class ParquetDatasource(_FileDatasource):
    def _read_file(self, path):
        import pyarrow.parquet as pq

        return pq.read_table(path)


class ParquetBulkDatasource(ParquetDatasource):
    """Explicit file list, NO directory/glob expansion or existence check
    up front (reference: read_parquet_bulk — the fast path for huge
    already-resolved file lists)."""

    def __init__(self, paths):
        if isinstance(paths, str):
            paths = [paths]
        self.paths = list(paths)


class CSVDatasource(_FileDatasource):
    def _read_file(self, path):
        from pyarrow import csv as pacsv

        return pacsv.read_csv(path)


class JSONDatasource(_FileDatasource):
    def _read_file(self, path):
        from pyarrow import json as pajson

        return pajson.read_json(path)


class ORCDatasource(_FileDatasource):
    def _read_file(self, path):
        from pyarrow import orc as paorc

        return paorc.read_table(path)


class FeatherDatasource(_FileDatasource):
    """Arrow IPC / Feather v2 files (reference: read_api.read_feather)."""

    def _read_file(self, path):
        from pyarrow import feather as pafeather

        return pafeather.read_table(path)


class RangeTensorDatasource(Datasource):
    """range_tensor(n, shape): each row is an ndarray of `shape` filled
    with its index (reference read_api.range_tensor — the standard data
    benchmark source)."""

    def __init__(self, n: int, shape):
        self.n = n
        self.shape = tuple(shape)

    def read_tasks(self, parallelism, limit):
        n = self.n if limit is None else min(self.n, limit)

        def make(lo, hi):
            def read():
                # Row cells are SHAPED ndarrays (NdarrayType extension
                # column), matching the reference's tensor-row semantics.
                return block_from_rows([
                    {"data": np.full(self.shape, i, dtype=np.int64)}
                    for i in range(lo, hi)])

            return read

        return [make(lo, hi) for lo, hi in _partition(n, parallelism)]


# ---- write path (per-block writers used by Dataset.write_*) --------------

def write_parquet_block(block, path: str, index: int) -> str:
    import os

    import pyarrow.parquet as pq

    out = os.path.join(path, f"part-{index:05d}.parquet")
    pq.write_table(block, out)
    return out


def write_csv_block(block, path: str, index: int) -> str:
    import os

    import pyarrow.csv as pacsv

    out = os.path.join(path, f"part-{index:05d}.csv")
    pacsv.write_csv(block, out)
    return out


def write_orc_block(block, path: str, index: int) -> str:
    import os

    import pyarrow.orc as paorc

    out = os.path.join(path, f"part-{index:05d}.orc")
    paorc.write_table(block, out)
    return out


def write_feather_block(block, path: str, index: int) -> str:
    import os

    import pyarrow.feather as pafeather

    out = os.path.join(path, f"part-{index:05d}.feather")
    pafeather.write_feather(block, out)
    return out


def write_text_block(block, path: str, index: int) -> str:
    """One line per row of the first (string) column."""
    import os

    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.txt")
    batch = BlockAccessor(block).to_batch()
    col = next(iter(batch.values()))
    with open(out, "w") as f:
        for v in col:
            f.write(str(v) + "\n")
    return out


def write_json_block(block, path: str, index: int) -> str:
    import json
    import os

    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.json")
    with open(out, "w") as f:
        for row in BlockAccessor(block).to_rows():
            f.write(json.dumps({k: v.item() if hasattr(v, "item") else v
                                for k, v in row.items()}) + "\n")
    return out


class TextDatasource(_FileDatasource):
    """One row per line (reference: read_api.py read_text)."""

    def _read_file(self, path):
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()   # handles \n and \r\n alike
        return block_from_batch({"text": np.asarray(lines, dtype=object)})


class BinaryDatasource(_FileDatasource):
    """One row per file: bytes + path (read_binary_files)."""

    def _read_file(self, path):
        with open(path, "rb") as f:
            data = f.read()
        return block_from_batch({
            "bytes": np.asarray([data], dtype=object),
            "path": np.asarray([path], dtype=object)})


class NumpyFileDatasource(_FileDatasource):
    """.npy (one unnamed column) or .npz (one column per array) files
    (read_numpy)."""

    def __init__(self, paths, column: str = "data"):
        super().__init__(paths)
        self.column = column

    def _read_file(self, path):
        loaded = np.load(path, allow_pickle=False)
        if isinstance(loaded, np.ndarray):
            return block_from_batch({self.column: loaded})
        return block_from_batch({k: loaded[k] for k in loaded.files})


class ImageDatasource(_FileDatasource):
    """Decoded HWC uint8 arrays (read_images; requires Pillow)."""

    def _read_file(self, path):
        try:
            from PIL import Image
        except ImportError as e:
            raise ImportError("read_images requires Pillow") from e
        with Image.open(path) as im:
            arr = np.asarray(im.convert("RGB"))
        # One array-valued row: a plain asarray([arr], dtype=object) would
        # explode the image into per-pixel Python objects.
        cell = np.empty(1, dtype=object)
        cell[0] = arr
        return block_from_batch({
            "image": cell, "path": np.asarray([path], dtype=object)})


class TFRecordDatasource(_FileDatasource):
    """tf.train.Example records without a TensorFlow dependency
    (reference: read_tfrecords; framing + proto codec in data/tfrecord.py)."""

    def _read_file(self, path):
        from ray_tpu.data import tfrecord

        rows = [tfrecord.decode_example(rec)
                for rec in tfrecord.read_records(path)]
        # Uniform columns: pad features absent in some records with None.
        keys: List[str] = []
        for r in rows:
            keys.extend(k for k in r if k not in keys)
        # decode_example always yields lists (the Example proto can't tell a
        # scalar from a 1-element list). Collapse a column to scalars only
        # when EVERY present value has length 1 — per-file-consistent, never
        # ragged within a column.
        scalar_cols = {
            k for k in keys
            if all(len(r[k]) == 1 for r in rows if r.get(k) is not None)}
        return block_from_rows([
            {k: (r[k][0] if k in scalar_cols else r[k])
             if r.get(k) is not None else None
             for k in keys}
            for r in rows])


class AvroDatasource(_FileDatasource):
    """Avro object container files, null/deflate codecs (reference:
    read_avro; the OCF codec lives in data/avro.py)."""

    def _read_file(self, path):
        from ray_tpu.data import avro as avro_mod

        _schema, rows = avro_mod.read_file(path)
        return block_from_rows(rows)


def write_tfrecords_block(block, path: str, index: int) -> str:
    from ray_tpu.data import tfrecord
    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.tfrecords")
    tfrecord.write_records(
        out, (tfrecord.encode_example(row)
              for row in BlockAccessor(block).to_rows()))
    return out


def write_avro_block(block, path: str, index: int) -> str:
    from ray_tpu.data import avro as avro_mod
    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.avro")
    rows = []
    for row in BlockAccessor(block).to_rows():
        rows.append({k: (v.item() if hasattr(v, "item")
                         and getattr(v, "ndim", 1) == 0 else v)
                     for k, v in row.items()})
    schema = avro_mod.infer_schema(rows or [{}])
    avro_mod.write_file(out, schema, rows)
    return out


class SQLDatasource(Datasource):
    """DBAPI reads (reference: read_sql over any PEP-249 connection).
    `connection_factory` must be picklable (read tasks run in workers)."""

    def __init__(self, sql: str, connection_factory: Callable):
        self.sql = sql
        self.connection_factory = connection_factory

    def read_tasks(self, parallelism, limit):
        sql, factory = self.sql, self.connection_factory
        lim = limit

        def read():
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                cols = [d[0] for d in cur.description]
                rows = cur.fetchall() if lim is None else cur.fetchmany(lim)
                return block_from_rows(
                    [dict(zip(cols, r)) for r in rows])
            finally:
                conn.close()

        return [read]


class WebDatasetDatasource(_FileDatasource):
    """Tar shards of grouped samples: files sharing a basename become one
    row keyed by extension (reference: read_webdataset)."""

    def _read_file(self, path):
        import tarfile

        samples: Dict[str, Dict[str, Any]] = {}
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not member.isfile():
                    continue
                dirname, _, fname = member.name.rpartition("/")
                base, dot, ext = fname.partition(".")
                if dirname:
                    base = f"{dirname}/{base}"
                data = tf.extractfile(member).read()
                samples.setdefault(base, {"__key__": base})[ext or "data"] = data
        # Ragged samples (an extension present in only some) pad with None:
        # block columns must be uniform.
        keys: List[str] = []
        for s in samples.values():
            keys.extend(k for k in s if k not in keys)
        rows = [{k: s.get(k) for k in keys} for s in samples.values()]
        return block_from_rows(rows)


class TorchDatasource(Datasource):
    """Map-style torch Dataset -> rows (reference: from_torch)."""

    def __init__(self, torch_dataset):
        self.ds = torch_dataset

    def read_tasks(self, parallelism, limit):
        n = len(self.ds)
        if limit is not None:
            n = min(n, limit)
        ds = self.ds
        return [lambda lo=lo, hi=hi: block_from_rows(
                    [{"item": ds[j]} for j in range(lo, hi)])
                for lo, hi in _partition(n, parallelism)]


def write_numpy_block(block, path: str, index: int) -> str:
    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.npz")
    batch = {}
    for k, v in BlockAccessor(block).to_batch().items():
        if v.dtype == object:
            # read_numpy loads with allow_pickle=False (untrusted files),
            # so object columns must become pickle-free U/S arrays here or
            # the round trip would fail.
            try:
                v = np.asarray(v.tolist())
                assert v.dtype != object
            except Exception:
                raise ValueError(
                    f"write_numpy: column {k!r} holds mixed/non-primitive "
                    "objects; only numeric, string, and bytes columns are "
                    "npz-serializable")
        batch[k] = v
    np.savez(out, **batch)
    return out
