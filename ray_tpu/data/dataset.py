"""Dataset: the lazy, streaming, distributed data API.

Reference analog: python/ray/data/dataset.py (map_batches:409, iter_batches
via iterator.py:94, read_api.py connectors). Plans build lazily; execution
streams blocks through the task runtime with backpressure (execution.py).
"""

from __future__ import annotations

import itertools
from builtins import range as _range
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.data import datasource as ds_mod
from ray_tpu.data import plan as plan_mod
from ray_tpu.data.block import Batch, Block, BlockAccessor


class Dataset:
    def __init__(self, ops: List[plan_mod.LogicalOp], parallelism: int = 8):
        self._ops = ops
        self._parallelism = parallelism
        self._last_stats = None  # DatasetStats of the most recent execution

    # ---- transforms (lazy) ----------------------------------------------

    def _with(self, op: plan_mod.LogicalOp) -> "Dataset":
        return Dataset(self._ops + [op], self._parallelism)

    def map_batches(self, fn: Callable[[Batch], Batch], *,
                    batch_size: Optional[int] = None,
                    fn_kwargs: Optional[dict] = None,
                    compute: str = "tasks",
                    concurrency: int = 2) -> "Dataset":
        """compute="actors" runs fn on a pool of stateful actors (fn may be
        a class instantiated once per actor — model-inference pattern)."""
        return self._with(plan_mod.MapBatches(fn, batch_size, fn_kwargs,
                                              compute, concurrency))

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._with(plan_mod.MapRows(fn))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return self._with(plan_mod.FlatMap(fn))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return self._with(plan_mod.FilterRows(fn))

    def limit(self, n: int) -> "Dataset":
        return self._with(plan_mod.Limit(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(plan_mod.Repartition(num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(plan_mod.RandomShuffle(seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(plan_mod.Sort(key, descending))

    # ---- execution -------------------------------------------------------

    def iter_blocks(self) -> Iterator[Block]:
        from ray_tpu.data.execution import DatasetStats, execute_streaming

        stats = DatasetStats()
        yield from execute_streaming(self._ops, self._parallelism,
                                     stats=stats)
        self._last_stats = stats.finalize()

    def stats(self) -> str:
        """Per-operator wall/blocks/rows/bytes of the most recent execution
        (reference analog: Dataset.stats(), data/_internal/stats.py).
        Executes the plan if it has not run yet."""
        if self._last_stats is None:
            for _ in self.iter_blocks():
                pass
        if self._last_stats is None:  # materialized: nothing executed
            return "No execution stats (already-materialized blocks)."
        return self._last_stats.summary()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     prefetch_batches: Optional[int] = None,
                     device_index: Optional[int] = None,
                     cursor=None) -> Iterator[Any]:
        """Streams batches. With `prefetch_batches=N` this returns a
        `StreamingIterator` (data/streaming.py): a producer thread overlaps
        read/transform/transfer with the consumer, up to N batches stay
        prefetched through a device ring, and the iterator carries a
        resumable cursor. Streaming batches never straddle block
        boundaries (exact cursors); the default sync path re-chunks
        across them."""
        if prefetch_batches is not None:
            from ray_tpu.data.streaming import make_local_iterator

            return make_local_iterator(
                self, batch_size=batch_size, batch_format=batch_format,
                drop_last=drop_last, prefetch_batches=prefetch_batches,
                device_index=device_index, cursor=cursor)
        return self._iter_batches_sync(batch_size=batch_size,
                                       batch_format=batch_format,
                                       drop_last=drop_last)

    def _iter_batches_sync(self, *, batch_size: Optional[int] = 256,
                           batch_format: str = "numpy",
                           drop_last: bool = False) -> Iterator[Any]:
        leftover: Optional[Block] = None
        for block in self.iter_blocks():
            if leftover is not None and leftover.num_rows:
                block = BlockAccessor.concat([leftover, block])
                leftover = None
            if batch_size is None:
                yield self._format(block, batch_format)
                continue
            acc = BlockAccessor(block)
            n = acc.num_rows()
            start = 0
            while n - start >= batch_size:
                yield self._format(acc.slice(start, start + batch_size),
                                   batch_format)
                start += batch_size
            if start < n:
                leftover = acc.slice(start, n)
        if leftover is not None and leftover.num_rows and not drop_last:
            yield self._format(leftover, batch_format)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu",
                           drop_last: bool = False) -> Iterator[Dict]:
        """numpy batches converted to torch tensors
        (Dataset.iter_torch_batches analog). Non-numeric columns pass
        through unconverted."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                try:
                    t = torch.as_tensor(v, device=device)
                except (TypeError, RuntimeError):
                    out[k] = v  # object/string columns stay numpy
                    continue
                if dtypes is not None:
                    want = (dtypes.get(k) if isinstance(dtypes, dict)
                            else dtypes)
                    if want is not None:
                        t = t.to(want)
                out[k] = t
            yield out

    def iter_rows(self) -> Iterator[Dict]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()

    @staticmethod
    def _format(block: Block, batch_format: str):
        if batch_format in ("numpy", "default"):
            return BlockAccessor(block).to_batch()
        if batch_format == "pandas":
            return BlockAccessor(block).to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return block
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # ---- consumption -----------------------------------------------------

    def take(self, n: int = 20) -> List[Dict]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Dict]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.num_rows for b in self.iter_blocks())

    def schema(self):
        for block in self.iter_blocks():
            return block.schema
        return None

    def materialize(self) -> "MaterializedDataset":
        return MaterializedDataset(list(self.iter_blocks()), self._parallelism)

    def to_pandas(self):
        return BlockAccessor.concat(list(self.iter_blocks())).to_pandas()

    # ---- column ops ------------------------------------------------------

    def add_column(self, name: str, fn: Callable[[Dict], Any]) -> "Dataset":
        def _add(batch):
            batch = dict(batch)
            batch[name] = np.asarray(fn(batch))
            return batch

        return self.map_batches(_add)

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(lambda b: {k: b[k] for k in cols})

    def drop_columns(self, cols: List[str]) -> "Dataset":
        drop = set(cols)
        return self.map_batches(
            lambda b: {k: v for k, v in b.items() if k not in drop})

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map_batches(
            lambda b: {mapping.get(k, k): v for k, v in b.items()})

    def random_sample(self, fraction: float, *, seed: Optional[int] = None
                      ) -> "Dataset":
        rng = np.random.default_rng(seed)

        def _sample(batch):
            n = len(next(iter(batch.values()), []))
            keep = rng.random(n) < fraction
            return {k: np.asarray(v)[keep] for k, v in batch.items()}

        return self.map_batches(_sample)

    # ---- combining -------------------------------------------------------

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = list(self.iter_blocks())
        for o in others:
            blocks.extend(o.iter_blocks())
        return MaterializedDataset(blocks, self._parallelism)

    def zip(self, other: "Dataset") -> "Dataset":
        """Horizontal combine: rows align positionally; column collisions
        take an _1 suffix on `other` (reference Dataset.zip semantics)."""
        import pyarrow as pa

        left = BlockAccessor.concat(list(self.iter_blocks()))
        right = BlockAccessor.concat(list(other.iter_blocks()))
        if left.num_rows != right.num_rows:
            raise ValueError(
                f"zip requires equal row counts ({left.num_rows} vs "
                f"{right.num_rows})")
        cols = {name: left.column(name) for name in left.column_names}
        for name in right.column_names:
            out = name if name not in cols else f"{name}_1"
            cols[out] = right.column(name)
        return MaterializedDataset([pa.table(cols)], self._parallelism)

    # ---- groupby ---------------------------------------------------------

    def groupby(self, key: str, *, num_partitions: Optional[int] = None):
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key, num_partitions)

    def sum(self, on: str):
        return float(sum(BlockAccessor(b).to_batch()[on].sum()
                         for b in self.iter_blocks() if b.num_rows))

    def min(self, on: str):
        return float(min(BlockAccessor(b).to_batch()[on].min()
                         for b in self.iter_blocks() if b.num_rows))

    def max(self, on: str):
        return float(max(BlockAccessor(b).to_batch()[on].max()
                         for b in self.iter_blocks() if b.num_rows))

    def mean(self, on: str):
        total, count = 0.0, 0
        for b in self.iter_blocks():
            if b.num_rows:
                total += float(BlockAccessor(b).to_batch()[on].sum())
                count += b.num_rows
        return total / max(count, 1)

    def std(self, on: str, ddof: int = 1):
        """One-pass stddev, SHIFTED by the first value seen: the naive
        sum/sumsq formula catastrophically cancels when |mean| >> spread
        (Dataset.std analog)."""
        import math

        shift = None
        total, sq, count = 0.0, 0.0, 0
        for b in self.iter_blocks():
            if b.num_rows:
                col = BlockAccessor(b).to_batch()[on].astype("float64")
                if shift is None:
                    shift = float(col[0])
                col = col - shift
                total += float(col.sum())
                sq += float((col * col).sum())
                count += b.num_rows
        if count <= ddof:
            return 0.0
        var = (sq - total * total / count) / (count - ddof)
        return math.sqrt(max(var, 0.0))

    def unique(self, on: str) -> List[Any]:
        """Distinct values of one column, first-seen order — unsorted,
        so None/mixed-type columns don't raise (Dataset.unique analog).
        Tensor cells (unhashable lists) dedupe by their tuple form."""
        def hashable(v):
            return (tuple(hashable(x) for x in v)
                    if isinstance(v, list) else v)

        seen: Dict[Any, Any] = {}
        for b in self.iter_blocks():
            if b.num_rows:
                for v in BlockAccessor(b).to_batch()[on].tolist():
                    seen.setdefault(hashable(v), v)
        return list(seen.values())

    def aggregate(self, **named_aggs: Tuple[str, str]):
        """Multi-aggregate in one pass: aggregate(total=("v", "sum"),
        hi=("v", "max")) -> {"total": ..., "hi": ...}
        (Dataset.aggregate(AggregateFn...) analog, column/op pairs)."""
        ops = {"sum", "min", "max", "mean", "count"}
        for name, (col, op) in named_aggs.items():
            if op not in ops:
                raise ValueError(f"{name}: unknown aggregate {op!r} "
                                 f"(one of {sorted(ops)})")
        # Pre-seed identities so an EMPTY dataset still returns every
        # requested key (count 0, sum 0.0, min/max/mean None).
        acc: Dict[str, Any] = {
            name: (0 if op == "count" else 0.0 if op in ("sum", "mean")
                   else None)
            for name, (_c, op) in named_aggs.items()}
        counts: Dict[str, int] = {}
        for b in self.iter_blocks():
            if not b.num_rows:
                continue
            batch = BlockAccessor(b).to_batch()
            for name, (col, op) in named_aggs.items():
                if op == "count":
                    acc[name] += b.num_rows
                    continue
                v = batch[col]
                if op in ("sum", "mean"):
                    acc[name] += float(v.sum())
                    counts[name] = counts.get(name, 0) + b.num_rows
                elif op == "min":
                    val = float(v.min())
                    acc[name] = (val if acc[name] is None
                                 else min(acc[name], val))
                elif op == "max":
                    val = float(v.max())
                    acc[name] = (val if acc[name] is None
                                 else max(acc[name], val))
        for name, (col, op) in named_aggs.items():
            if op == "mean":
                n = counts.get(name, 0)
                acc[name] = acc[name] / n if n else None
        return acc

    # ---- writes (datasource write path) ----------------------------------

    def _write(self, path: str, writer_name: str) -> List[str]:
        """One remote write task per block -> <path>/part-NNNNN.<ext>."""
        import os

        os.makedirs(path, exist_ok=True)
        writer = getattr(ds_mod, writer_name)
        write_task = ray_tpu_remote_write()
        refs = [write_task.remote(writer, block, path, i)
                for i, block in enumerate(self.iter_blocks())]
        import ray_tpu

        return ray_tpu.get(refs)

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "write_parquet_block")

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "write_csv_block")

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "write_json_block")

    def write_numpy(self, path: str) -> List[str]:
        return self._write(path, "write_numpy_block")

    def write_tfrecords(self, path: str) -> List[str]:
        return self._write(path, "write_tfrecords_block")

    def write_avro(self, path: str) -> List[str]:
        return self._write(path, "write_avro_block")

    def write_orc(self, path: str) -> List[str]:
        return self._write(path, "write_orc_block")

    def write_feather(self, path: str) -> List[str]:
        return self._write(path, "write_feather_block")

    def write_text(self, path: str) -> List[str]:
        return self._write(path, "write_text_block")

    # ---- train ingestion -------------------------------------------------

    def streaming_split(self, n: int, *, equal: bool = False,
                        seed: Optional[int] = None,
                        batch_size: Optional[int] = 256,
                        batch_format: str = "numpy",
                        drop_last: bool = False,
                        prefetch_batches: int = 2,
                        device_index: Optional[int] = None):
        """N disjoint `StreamShard`s over ONE shared pipelined execution
        (data/streaming.py): a coordinator actor streams block refs with
        bounded in-flight, shard r takes seeded-permuted positions
        r, r+n, ... — no driver materialization. Same seed + world gives
        a bit-identical global visit order; `equal=True` trims the tail
        remainder so every shard sees the same block count.

        Reference analog: Dataset.streaming_split used by Train's
        DataConfig."""
        from ray_tpu.data.streaming import make_stream_shards

        return make_stream_shards(
            self, n, equal=equal, seed=seed, batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
            prefetch_batches=prefetch_batches, device_index=device_index)

    def split(self, n: int) -> List["MaterializedDataset"]:
        blocks = list(self.iter_blocks())
        shards: List[List[Block]] = [[] for _ in _range(n)]
        for i, b in enumerate(blocks):
            shards[i % n].append(b)
        return [MaterializedDataset(s, self._parallelism) for s in shards]

    def split_at_indices(self, indices: List[int]
                         ) -> List["MaterializedDataset"]:
        """Split at ROW indices (Dataset.split_at_indices analog):
        [3, 7] -> rows [0,3), [3,7), [7,end)."""
        if sorted(indices) != list(indices) or any(i < 0 for i in indices):
            raise ValueError("indices must be non-negative and sorted")
        bounds = [0, *indices, None]
        rows_seen = 0
        blocks = list(self.iter_blocks())
        shards: List[List[Block]] = [[] for _ in _range(len(bounds) - 1)]
        for b in blocks:
            lo = rows_seen
            hi = rows_seen + b.num_rows
            for k in _range(len(bounds) - 1):
                s_lo = bounds[k]
                s_hi = bounds[k + 1]
                cut_lo = max(lo, s_lo)
                cut_hi = hi if s_hi is None else min(hi, s_hi)
                if cut_hi > cut_lo:
                    shards[k].append(b.slice(cut_lo - lo, cut_hi - cut_lo))
            rows_seen = hi
        return [MaterializedDataset(s, self._parallelism) for s in shards]

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["MaterializedDataset",
                                    "MaterializedDataset"]:
        """(train, test) row split (Dataset.train_test_split analog)."""
        if not 0.0 < test_size < 1.0:
            raise ValueError("test_size must be in (0, 1)")
        ds: "Dataset" = self
        if shuffle:
            ds = ds.random_shuffle(seed=seed)
        blocks = list(ds.iter_blocks())
        total = sum(b.num_rows for b in blocks)
        cut = total - int(total * test_size)
        mat = MaterializedDataset(blocks, self._parallelism)
        train, test = mat.split_at_indices([cut])
        return train, test


class MaterializedDataset(Dataset):
    def __init__(self, blocks: List[Block], parallelism: int = 8):
        self._blocks = blocks
        self._parallelism = parallelism
        self._ops = []
        self._last_stats = None

    def iter_blocks(self) -> Iterator[Block]:
        yield from self._blocks

    def _with(self, op):
        # Transforms on materialized data re-enter the lazy path.
        ds = from_blocks(self._blocks, self._parallelism)
        return ds._with(op)


class DataIterator:
    """Per-worker view for train ingestion (reference: DataIterator
    iterator.py:94)."""

    def __init__(self, dataset: Dataset):
        self._ds = dataset

    def iter_batches(self, **kwargs):
        return self._ds.iter_batches(**kwargs)

    def count(self):
        return self._ds.count()


# ---- read API (reference: read_api.py) -----------------------------------

def _run_write(writer, block, path, index):
    return writer(block, path, index)


def ray_tpu_remote_write():
    import ray_tpu

    return ray_tpu.remote(_run_write)


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset([plan_mod.Read(ds_mod.RangeDatasource(n), parallelism)],
                   parallelism)


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.ItemsDatasource(items), parallelism)],
                   parallelism)


def from_numpy(arrays: Dict[str, np.ndarray], *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.NumpyDatasource(arrays), parallelism)],
                   parallelism)


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df, preserve_index=False)
    return from_blocks([table], parallelism)


def from_blocks(blocks: List[Block], parallelism: int = 8) -> Dataset:
    class _BlocksSource(ds_mod.Datasource):
        def read_tasks(self, parallelism_, limit):
            return [lambda b=b: b for b in blocks]

    return Dataset([plan_mod.Read(_BlocksSource(), parallelism)], parallelism)


def read_parquet(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.ParquetDatasource(paths), parallelism)],
                   parallelism)


def read_csv(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.CSVDatasource(paths), parallelism)],
                   parallelism)


def read_json(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.JSONDatasource(paths), parallelism)],
                   parallelism)


def read_text(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.TextDatasource(paths), parallelism)],
                   parallelism)


def read_binary_files(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.BinaryDatasource(paths), parallelism)],
                   parallelism)


def read_numpy(paths, *, column: str = "data", parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(
        ds_mod.NumpyFileDatasource(paths, column), parallelism)], parallelism)


def read_images(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.ImageDatasource(paths), parallelism)],
                   parallelism)


def read_sql(sql: str, connection_factory, *, parallelism: int = 1) -> Dataset:
    return Dataset([plan_mod.Read(
        ds_mod.SQLDatasource(sql, connection_factory), parallelism)],
        parallelism)


def read_webdataset(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(
        ds_mod.WebDatasetDatasource(paths), parallelism)], parallelism)


def read_tfrecords(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(
        ds_mod.TFRecordDatasource(paths), parallelism)], parallelism)


def read_avro(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(
        ds_mod.AvroDatasource(paths), parallelism)], parallelism)


def read_orc(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.ORCDatasource(paths), parallelism)],
                   parallelism)


def read_feather(paths, *, parallelism: int = 8) -> Dataset:
    """Arrow IPC / Feather v2 (reference: read_api.read_feather)."""
    return Dataset([plan_mod.Read(
        ds_mod.FeatherDatasource(paths), parallelism)], parallelism)


# ---- extended catalog (data/connectors.py) --------------------------------

def read_parquet_bulk(paths, *, parallelism: int = 8) -> Dataset:
    """One read task per explicitly-listed file, no directory/metadata
    inference (reference: read_api.read_parquet_bulk — the fast path for
    huge file lists)."""
    return Dataset([plan_mod.Read(
        ds_mod.ParquetBulkDatasource(paths), parallelism)], parallelism)


def read_delta(table_path: str, *, version=None,
               parallelism: int = 8) -> Dataset:
    """Delta Lake table at its latest (or a pinned) version (reference:
    read_api.read_delta). Self-contained: replays the JSON transaction
    log; no deltalake client needed."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(
        connectors.DeltaDatasource(table_path, version), parallelism)],
        parallelism)


def read_audio(paths, *, parallelism: int = 8) -> Dataset:
    """Audio files -> {"amplitude", "sample_rate", "path"} rows
    (reference: read_api.read_audio). WAV is native; other codecs need
    soundfile."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(
        connectors.AudioDatasource(paths), parallelism)], parallelism)


def read_videos(paths, *, parallelism: int = 8) -> Dataset:
    """Video frames, one row each (reference: read_api.read_videos;
    requires cv2)."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(
        connectors.VideoDatasource(paths), parallelism)], parallelism)


def read_mongo(uri: str, database: str, collection: str, *, pipeline=None,
               parallelism: int = 1) -> Dataset:
    """MongoDB collection/aggregation (reference: read_api.read_mongo;
    requires pymongo)."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(connectors.MongoDatasource(
        uri, database, collection, pipeline), parallelism)], parallelism)


def read_bigquery(project_id: str, query: str, *,
                  parallelism: int = 1) -> Dataset:
    """BigQuery SQL result (reference: read_api.read_bigquery; requires
    google-cloud-bigquery)."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(connectors.BigQueryDatasource(
        project_id, query), parallelism)], parallelism)


def read_clickhouse(dsn: str, query: str, *,
                    parallelism: int = 1) -> Dataset:
    """ClickHouse query result (reference: read_api.read_clickhouse;
    requires clickhouse-connect)."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(connectors.ClickHouseDatasource(
        dsn, query), parallelism)], parallelism)


def read_databricks_tables(server_hostname: str, http_path: str,
                           token: str, query: str, *,
                           parallelism: int = 1) -> Dataset:
    """Databricks SQL warehouse query (reference:
    read_api.read_databricks_tables; requires databricks-sql-connector)."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(connectors.DatabricksDatasource(
        server_hostname, http_path, token, query), parallelism)],
        parallelism)


def read_lance(uri: str, *, columns=None, parallelism: int = 1) -> Dataset:
    """Lance dataset (reference: read_api.read_lance; requires lance)."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(connectors.LanceDatasource(
        uri, columns), parallelism)], parallelism)


def read_iceberg(table_identifier: str, *, catalog_kwargs=None,
                 parallelism: int = 1) -> Dataset:
    """Iceberg table scan (reference: read_api.read_iceberg; requires
    pyiceberg)."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(connectors.IcebergDatasource(
        table_identifier, catalog_kwargs), parallelism)], parallelism)


def read_hudi(table_uri: str, *, parallelism: int = 1) -> Dataset:
    """Hudi table snapshot (reference: read_api.read_hudi; requires
    hudi)."""
    from ray_tpu.data import connectors

    return Dataset([plan_mod.Read(connectors.HudiDatasource(table_uri),
                                  parallelism)], parallelism)


def from_dask(ddf) -> Dataset:
    """Dask collection -> Dataset, partitions computed via the ray_tpu
    dask scheduler (reference: read_api.from_dask; requires dask)."""
    from ray_tpu.data import connectors

    import pyarrow as pa

    # One block per dask partition — never pd.concat on the driver (that
    # would double peak memory and collapse the collection's parallelism
    # into a single giant block).
    return from_blocks([pa.Table.from_pandas(p, preserve_index=False)
                        for p in connectors.dask_partitions(ddf)])


def from_modin(df) -> Dataset:
    """Modin dataframe -> Dataset (reference: read_api.from_modin)."""
    from ray_tpu.data import connectors

    return from_pandas(connectors.dataframe_from(df, "modin"))


def from_mars(df) -> Dataset:
    """Mars dataframe -> Dataset (reference: read_api.from_mars)."""
    from ray_tpu.data import connectors

    return from_pandas(connectors.dataframe_from(df, "mars"))


def from_daft(df) -> Dataset:
    """Daft dataframe -> Dataset (reference: read_api.from_daft)."""
    from ray_tpu.data import connectors

    return from_pandas(connectors.dataframe_from(df, "daft"))


def from_spark(df) -> Dataset:
    """Spark dataframe -> Dataset (reference: read_api.from_spark)."""
    from ray_tpu.data import connectors

    return from_pandas(connectors.dataframe_from(df, "spark"))


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    """Rows of index-filled ndarrays (reference: read_api.range_tensor,
    the standard data-benchmark source)."""
    return Dataset([plan_mod.Read(
        ds_mod.RangeTensorDatasource(n, shape), parallelism)], parallelism)


def from_jax(arrays, *, parallelism: int = 8) -> Dataset:
    """jax.Arrays -> Dataset (device -> host once, then Arrow blocks).
    TPU-native addition: training evals feed straight from device output."""
    import numpy as _np

    if not isinstance(arrays, dict):
        arrays = {"data": arrays}
    host = {k: _np.asarray(v) for k, v in arrays.items()}
    return from_numpy(host, parallelism=parallelism)


def from_arrow(tables, *, parallelism: int = 8) -> Dataset:
    tables = [tables] if not isinstance(tables, (list, tuple)) else list(tables)
    return from_blocks(tables, parallelism)


def from_torch(torch_dataset, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(
        ds_mod.TorchDatasource(torch_dataset), parallelism)], parallelism)


def from_huggingface(hf_dataset, *, parallelism: int = 8) -> Dataset:
    """HuggingFace datasets arrive as Arrow under the hood (reference:
    read_api.from_huggingface)."""
    table = hf_dataset.data.table if hasattr(hf_dataset, "data") else None
    if table is None:
        raise TypeError("expected a huggingface datasets.Dataset")
    return from_blocks([table.combine_chunks()], parallelism)
