"""Dataset: the lazy, streaming, distributed data API.

Reference analog: python/ray/data/dataset.py (map_batches:409, iter_batches
via iterator.py:94, read_api.py connectors). Plans build lazily; execution
streams blocks through the task runtime with backpressure (execution.py).
"""

from __future__ import annotations

import itertools
from builtins import range as _range
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.data import datasource as ds_mod
from ray_tpu.data import plan as plan_mod
from ray_tpu.data.block import Batch, Block, BlockAccessor


class Dataset:
    def __init__(self, ops: List[plan_mod.LogicalOp], parallelism: int = 8):
        self._ops = ops
        self._parallelism = parallelism

    # ---- transforms (lazy) ----------------------------------------------

    def _with(self, op: plan_mod.LogicalOp) -> "Dataset":
        return Dataset(self._ops + [op], self._parallelism)

    def map_batches(self, fn: Callable[[Batch], Batch], *,
                    batch_size: Optional[int] = None,
                    fn_kwargs: Optional[dict] = None) -> "Dataset":
        return self._with(plan_mod.MapBatches(fn, batch_size, fn_kwargs))

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._with(plan_mod.MapRows(fn))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return self._with(plan_mod.FlatMap(fn))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return self._with(plan_mod.FilterRows(fn))

    def limit(self, n: int) -> "Dataset":
        return self._with(plan_mod.Limit(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(plan_mod.Repartition(num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(plan_mod.RandomShuffle(seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(plan_mod.Sort(key, descending))

    # ---- execution -------------------------------------------------------

    def iter_blocks(self) -> Iterator[Block]:
        from ray_tpu.data.execution import execute_streaming

        yield from execute_streaming(self._ops, self._parallelism)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        """Streams batches, re-chunking across block boundaries."""
        leftover: Optional[Block] = None
        for block in self.iter_blocks():
            if leftover is not None and leftover.num_rows:
                block = BlockAccessor.concat([leftover, block])
                leftover = None
            if batch_size is None:
                yield self._format(block, batch_format)
                continue
            acc = BlockAccessor(block)
            n = acc.num_rows()
            start = 0
            while n - start >= batch_size:
                yield self._format(acc.slice(start, start + batch_size),
                                   batch_format)
                start += batch_size
            if start < n:
                leftover = acc.slice(start, n)
        if leftover is not None and leftover.num_rows and not drop_last:
            yield self._format(leftover, batch_format)

    def iter_rows(self) -> Iterator[Dict]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()

    @staticmethod
    def _format(block: Block, batch_format: str):
        if batch_format in ("numpy", "default"):
            return BlockAccessor(block).to_batch()
        if batch_format == "pandas":
            return BlockAccessor(block).to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return block
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # ---- consumption -----------------------------------------------------

    def take(self, n: int = 20) -> List[Dict]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Dict]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.num_rows for b in self.iter_blocks())

    def schema(self):
        for block in self.iter_blocks():
            return block.schema
        return None

    def materialize(self) -> "MaterializedDataset":
        return MaterializedDataset(list(self.iter_blocks()), self._parallelism)

    def to_pandas(self):
        return BlockAccessor.concat(list(self.iter_blocks())).to_pandas()

    # ---- train ingestion -------------------------------------------------

    def streaming_split(self, n: int) -> List["DataIterator"]:
        """N disjoint iterators (one per train worker), round-robin blocks.

        Reference analog: Dataset.streaming_split used by Train's DataConfig.
        """
        blocks = list(self.iter_blocks())  # materialized split (round 1)
        shards: List[List[Block]] = [[] for _ in _range(n)]
        for i, b in enumerate(blocks):
            shards[i % n].append(b)
        return [DataIterator(MaterializedDataset(s, self._parallelism))
                for s in shards]

    def split(self, n: int) -> List["MaterializedDataset"]:
        blocks = list(self.iter_blocks())
        shards: List[List[Block]] = [[] for _ in _range(n)]
        for i, b in enumerate(blocks):
            shards[i % n].append(b)
        return [MaterializedDataset(s, self._parallelism) for s in shards]


class MaterializedDataset(Dataset):
    def __init__(self, blocks: List[Block], parallelism: int = 8):
        self._blocks = blocks
        self._parallelism = parallelism
        self._ops = []

    def iter_blocks(self) -> Iterator[Block]:
        yield from self._blocks

    def _with(self, op):
        # Transforms on materialized data re-enter the lazy path.
        ds = from_blocks(self._blocks, self._parallelism)
        return ds._with(op)


class DataIterator:
    """Per-worker view for train ingestion (reference: DataIterator
    iterator.py:94)."""

    def __init__(self, dataset: Dataset):
        self._ds = dataset

    def iter_batches(self, **kwargs):
        return self._ds.iter_batches(**kwargs)

    def count(self):
        return self._ds.count()


# ---- read API (reference: read_api.py) -----------------------------------

def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset([plan_mod.Read(ds_mod.RangeDatasource(n), parallelism)],
                   parallelism)


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.ItemsDatasource(items), parallelism)],
                   parallelism)


def from_numpy(arrays: Dict[str, np.ndarray], *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.NumpyDatasource(arrays), parallelism)],
                   parallelism)


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df, preserve_index=False)
    return from_blocks([table], parallelism)


def from_blocks(blocks: List[Block], parallelism: int = 8) -> Dataset:
    class _BlocksSource(ds_mod.Datasource):
        def read_tasks(self, parallelism_, limit):
            return [lambda b=b: b for b in blocks]

    return Dataset([plan_mod.Read(_BlocksSource(), parallelism)], parallelism)


def read_parquet(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.ParquetDatasource(paths), parallelism)],
                   parallelism)


def read_csv(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.CSVDatasource(paths), parallelism)],
                   parallelism)


def read_json(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([plan_mod.Read(ds_mod.JSONDatasource(paths), parallelism)],
                   parallelism)
