"""Batch LLM inference over Data: the build_llm_processor analog.

Reference analog: python/ray/llm/_internal/batch/processor/base.py:44
(Processor = a chain of stages applied to a Dataset) and the stage set under
_internal/batch/stages/ (ChatTemplateStage, TokenizeStage,
vLLMEngineStage, DetokenizeStage), surfaced as
ray.data.llm.build_llm_processor (data/llm.py:160). Ours runs the NATIVE
paged-attention engine inside an actor-pool map_batches stage (stateful:
one engine per actor, model loaded once), with tokenize/detokenize and
chat-template stages as plain task maps around it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class ProcessorConfig:
    """Engine-stage knobs (vLLMEngineProcessorConfig analog)."""
    model_config: Any = None          # llama.LlamaConfig
    params_checkpoint: Optional[str] = None
    seed: int = 0
    num_kv_blocks: int = 256
    block_size: int = 16
    max_batch_size: int = 8
    prefill_chunk: int = 128
    concurrency: int = 1              # engine actors
    batch_size: int = 16              # rows per engine call
    # sampling defaults, overridable per row via a "sampling_params" column
    max_tokens: int = 32
    temperature: float = 0.0


class _EngineStage:
    """Stateful actor callable: one engine per actor, continuous batching
    within each incoming block."""

    def __init__(self, config: ProcessorConfig):
        import jax

        from ray_tpu.llm.engine import LLMEngine
        from ray_tpu.llm.model_runner import ModelRunner
        from ray_tpu.models import llama

        model_config = config.model_config or llama.LlamaConfig.tiny()
        if config.params_checkpoint:
            from ray_tpu.train.checkpoint import Checkpoint

            params = Checkpoint(config.params_checkpoint).load_pytree()
        else:
            params = llama.init_params(model_config,
                                       jax.random.key(config.seed))
        runner = ModelRunner(model_config, params,
                             num_blocks=config.num_kv_blocks,
                             block_size=config.block_size,
                             chunk_size=config.prefill_chunk)
        self.engine = LLMEngine(runner,
                                max_batch_size=config.max_batch_size,
                                prefill_chunk=config.prefill_chunk)
        self.config = config
        # The actor pool may overlap transform() calls (max_concurrency);
        # the engine's donated-cache step is single-flight.
        import threading

        self._lock = threading.Lock()

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            return self._generate(batch)

    def _generate(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.llm.sampling import SamplingParams

        prompts = [list(map(int, p)) for p in batch["prompt_token_ids"]]
        per_row = batch.get("sampling_params")
        ids = []
        for i, p in enumerate(prompts):
            overrides = dict(per_row[i]) if per_row is not None else {}
            sp = SamplingParams(
                max_tokens=int(overrides.get("max_tokens",
                                             self.config.max_tokens)),
                temperature=float(overrides.get("temperature",
                                                self.config.temperature)),
                top_k=int(overrides.get("top_k", 0)),
                top_p=float(overrides.get("top_p", 1.0)),
                seed=overrides.get("seed"))
            ids.append(self.engine.add_request(p, sp))
        done: Dict[str, Any] = {}
        while self.engine.has_unfinished():
            for out in self.engine.step():
                if out.finished:
                    done[out.request_id] = out
        outs = [done[i] for i in ids]
        result = dict(batch)
        result["generated_token_ids"] = [o.output_token_ids for o in outs]
        result["finish_reason"] = [o.finish_reason for o in outs]
        return result


class Processor:
    """A reusable pipeline: ds -> preprocess -> tokenize -> engine ->
    detokenize -> postprocess. Call it on a Dataset to get a lazy Dataset
    with generation columns appended."""

    def __init__(self, config: ProcessorConfig, *, tokenizer=None,
                 chat_template=None,
                 preprocess: Optional[Callable[[Dict], Dict]] = None,
                 postprocess: Optional[Callable[[Dict], Dict]] = None):
        self.config = config
        self.tokenizer = tokenizer
        self.chat_template = chat_template
        self.preprocess = preprocess
        self.postprocess = postprocess

    # Each stage is a top-level-picklable callable built here.

    def _tokenize_stage(self):
        tokenizer, template = self.tokenizer, self.chat_template

        def tokenize(row: Dict) -> Dict:
            if "prompt_token_ids" in row:
                return row
            if "messages" in row and template is not None:
                row["prompt_token_ids"] = template.render(row["messages"])
            elif "prompt" in row and tokenizer is not None:
                row["prompt_token_ids"] = tokenizer.encode(row["prompt"])
            else:
                raise ValueError(
                    "row needs prompt_token_ids, or prompt+tokenizer, or "
                    "messages+chat_template")
            return row

        return tokenize

    def _detokenize_stage(self):
        tokenizer = self.tokenizer

        def detokenize(row: Dict) -> Dict:
            if tokenizer is not None and "generated_token_ids" in row:
                try:
                    row["generated_text"] = tokenizer.decode(
                        list(map(int, row["generated_token_ids"])))
                except Exception:
                    row["generated_text"] = None
            return row

        return detokenize

    def __call__(self, ds):
        if self.preprocess is not None:
            ds = ds.map(self.preprocess)
        ds = ds.map(self._tokenize_stage())
        config = self.config

        class _BoundEngineStage(_EngineStage):
            # Actor-pool classes are instantiated with no args; bind the
            # processor config via closure (cloudpickle carries it).
            def __init__(self):
                super().__init__(config)

        ds = ds.map_batches(_BoundEngineStage,
                            batch_size=self.config.batch_size,
                            compute="actors",
                            concurrency=self.config.concurrency)
        ds = ds.map(self._detokenize_stage())
        if self.postprocess is not None:
            ds = ds.map(self.postprocess)
        return ds


def build_llm_processor(config: ProcessorConfig, *, tokenizer=None,
                        chat_template=None, preprocess=None,
                        postprocess=None) -> Processor:
    """ray.data.llm.build_llm_processor analog (reference data/llm.py:160)."""
    return Processor(config, tokenizer=tokenizer, chat_template=chat_template,
                     preprocess=preprocess, postprocess=postprocess)
