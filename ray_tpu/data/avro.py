"""Avro Object Container File I/O without the avro package.

Reference analog: python/ray/data/read_api.py read_avro (delegates to the
`avro`/`fastavro` packages). The OCF format is small enough to speak
directly: header (magic, metadata map with JSON schema + codec, 16-byte
sync marker) followed by data blocks (record count, byte size, payload,
sync marker). Codecs: null and deflate (raw RFC-1951, no zlib header).

Supported schema subset — the types a columnar pipeline produces:
null, boolean, int, long, float, double, bytes, string, enum, fixed,
record (named fields), array, map, and unions thereof.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Tuple

MAGIC = b"Obj\x01"


# ------------------------------------------------------------ primitives

def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(out: io.BytesIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def read_long(buf: io.BytesIO) -> int:
    result = shift = 0
    while True:
        raw = buf.read(1)
        if not raw:
            raise EOFError("truncated avro varint")
        b = raw[0]
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(result)
        shift += 7


def _write_bytes(out, data: bytes) -> None:
    write_long(out, len(data))
    out.write(data)


def _read_bytes(buf) -> bytes:
    n = read_long(buf)
    return buf.read(n)


# ------------------------------------------------------------ datum codec

def write_datum(out, schema, value) -> None:
    stype = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(stype, list):  # union spelled as the schema itself
        schema, stype = {"type": stype}, "union"
    if isinstance(schema, dict) and isinstance(schema.get("type"), list):
        stype = "union"
    if stype == "union":
        branches = schema["type"] if isinstance(schema, dict) else schema
        idx = _union_index(branches, value)
        write_long(out, idx)
        write_datum(out, branches[idx], value)
    elif stype == "null":
        pass
    elif stype == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif stype in ("int", "long"):
        write_long(out, int(value))
    elif stype == "float":
        out.write(struct.pack("<f", float(value)))
    elif stype == "double":
        out.write(struct.pack("<d", float(value)))
    elif stype == "bytes":
        if isinstance(value, (bytes, bytearray)):
            _write_bytes(out, bytes(value))
        elif isinstance(value, str):
            _write_bytes(out, value.encode("utf-8"))
        else:
            # bytes(int) would write a NUL run — stringify instead.
            _write_bytes(out, str(value).encode("utf-8"))
    elif stype == "string":
        if isinstance(value, str):
            _write_bytes(out, value.encode("utf-8"))
        elif isinstance(value, (bytes, bytearray)):
            _write_bytes(out, bytes(value))
        else:
            # Heterogenous columns infer "string"; stringify explicitly
            # (bytes(int) would silently write NUL runs — never that).
            _write_bytes(out, str(value).encode("utf-8"))
    elif stype == "enum":
        write_long(out, schema["symbols"].index(value))
    elif stype == "fixed":
        out.write(bytes(value))
    elif stype == "record":
        for field in schema["fields"]:
            # .get: sparse rows are legal (infer_schema makes the field a
            # nullable union, whose null branch encodes the None).
            write_datum(out, field["type"], value.get(field["name"]))
    elif stype == "array":
        items = list(value)
        if items:
            write_long(out, len(items))
            for item in items:
                write_datum(out, schema["items"], item)
        write_long(out, 0)
    elif stype == "map":
        if value:
            write_long(out, len(value))
            for k, v in value.items():
                _write_bytes(out, k.encode("utf-8"))
                write_datum(out, schema["values"], v)
        write_long(out, 0)
    else:
        raise ValueError(f"unsupported avro type {stype!r}")


def _union_index(branches, value) -> int:
    import numpy as np

    def name(b):
        return b["type"] if isinstance(b, dict) else b

    if value is None:
        return next(i for i, b in enumerate(branches) if name(b) == "null")
    for i, b in enumerate(branches):
        n = name(b)
        if n == "null":
            continue
        if n == "boolean" and isinstance(value, (bool, np.bool_)):
            return i
        if n in ("int", "long") and isinstance(value, (int, np.integer)) \
                and not isinstance(value, (bool, np.bool_)):
            return i
        if n in ("float", "double") and isinstance(value,
                                                   (float, np.floating)):
            return i
        if n == "string" and isinstance(value, str):
            return i
        if n == "bytes" and isinstance(value, (bytes, bytearray)):
            return i
        if n in ("record", "array", "map", "enum", "fixed"):
            return i
    # Fall back to the first non-null branch.
    return next(i for i, b in enumerate(branches) if name(b) != "null")


def read_datum(buf, schema):
    stype = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(stype, list):
        branches = stype
        idx = read_long(buf)
        return read_datum(buf, branches[idx])
    if stype == "union":
        branches = schema["type"]
        idx = read_long(buf)
        return read_datum(buf, branches[idx])
    if stype == "null":
        return None
    if stype == "boolean":
        return buf.read(1) == b"\x01"
    if stype in ("int", "long"):
        return read_long(buf)
    if stype == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if stype == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if stype == "bytes":
        return _read_bytes(buf)
    if stype == "string":
        return _read_bytes(buf).decode("utf-8")
    if stype == "enum":
        return schema["symbols"][read_long(buf)]
    if stype == "fixed":
        return buf.read(schema["size"])
    if stype == "record":
        return {f["name"]: read_datum(buf, f["type"])
                for f in schema["fields"]}
    if stype == "array":
        out: List = []
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:  # block with byte size prefix
                read_long(buf)
                count = -count
            for _ in range(count):
                out.append(read_datum(buf, schema["items"]))
    if stype == "map":
        result: Dict = {}
        while True:
            count = read_long(buf)
            if count == 0:
                return result
            if count < 0:
                read_long(buf)
                count = -count
            for _ in range(count):
                k = _read_bytes(buf).decode("utf-8")
                result[k] = read_datum(buf, schema["values"])
    raise ValueError(f"unsupported avro type {stype!r}")


# ----------------------------------------------------------- file format

def write_file(path: str, schema: Dict, rows: List[Dict], *,
               codec: str = "deflate", records_per_block: int = 4096) -> int:
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec {codec!r}")
    sync = os.urandom(16)
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = io.BytesIO()
        entries = {"avro.schema": json.dumps(schema).encode("utf-8"),
                   "avro.codec": codec.encode("utf-8")}
        write_long(meta, len(entries))
        for k, v in entries.items():
            _write_bytes(meta, k.encode("utf-8"))
            _write_bytes(meta, v)
        write_long(meta, 0)
        f.write(meta.getvalue())
        f.write(sync)
        for start in range(0, len(rows), records_per_block):
            chunk = rows[start:start + records_per_block]
            body = io.BytesIO()
            for row in chunk:
                write_datum(body, schema, row)
            payload = body.getvalue()
            if codec == "deflate":
                comp = zlib.compressobj(wbits=-15)
                payload = comp.compress(payload) + comp.flush()
            head = io.BytesIO()
            write_long(head, len(chunk))
            write_long(head, len(payload))
            f.write(head.getvalue())
            f.write(payload)
            f.write(sync)
    return len(rows)


def read_file(path: str) -> Tuple[Dict, List[Dict]]:
    with open(path, "rb") as f:
        raw = f.read()
    buf = io.BytesIO(raw)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path}: not an avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        count = read_long(buf)
        if count == 0:
            break
        if count < 0:
            read_long(buf)
            count = -count
        for _ in range(count):
            k = _read_bytes(buf).decode("utf-8")
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate"):
        raise ValueError(f"{path}: unsupported codec {codec!r}")
    sync = buf.read(16)
    rows: List[Dict] = []
    while buf.tell() < len(raw):
        count = read_long(buf)
        size = read_long(buf)
        payload = buf.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, wbits=-15)
        block = io.BytesIO(payload)
        for _ in range(count):
            rows.append(read_datum(block, schema))
        if buf.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt block)")
    return schema, rows


# ------------------------------------------------------- schema inference

def _primitive_type(sample) -> str:
    import numpy as np

    if isinstance(sample, (bool, np.bool_)):
        return "boolean"
    if isinstance(sample, (int, np.integer)):
        return "long"
    if isinstance(sample, (float, np.floating)):
        return "double"
    if isinstance(sample, (bytes, bytearray)):
        return "bytes"
    return "string"


def _merged_primitive_type(samples):
    """Type covering EVERY sample, not just the first. Lossless rules only:
    a column mixing ints and floats infers 'double' (a numeric widening —
    inferring 'long' from the first row would truncate 2.5 -> 2 at write
    time); ANY other mix becomes a real Avro union of the observed branch
    types (write_datum tags each value with its branch), never a silent
    stringification — [True, 2.5] must round-trip as [True, 2.5], not
    ['True', '2.5']."""
    types: List[str] = []
    for s in samples:
        if s is None:
            continue
        t = _primitive_type(s)
        if t not in types:
            types.append(t)
    if not types:
        return "string"
    if set(types) == {"long", "double"}:
        return "double"
    if len(types) == 1:
        return types[0]
    return types  # union spelled as the schema itself (Avro spec 1.11 §Unions)


def infer_schema(rows: List[Dict], name: str = "Row") -> Dict:
    """Record schema from sample rows; columns with missing/None values
    become nullable unions. Array item and map value types cover every
    element seen across the sample (mixed int/float promotes to double)."""
    import numpy as np

    fields = []
    # Ordered union of all row keys (first-seen order): rows may be sparse.
    keys: List[str] = []
    seen = set()
    for r in rows:
        for k in r:
            if k not in seen:
                seen.add(k)
                keys.append(k)
    for k in keys:
        values = [r.get(k) for r in rows]
        nullable = any(v is None for v in values)  # .get: missing key -> None
        sample = next((v for v in values if v is not None), None)
        if isinstance(sample, (list, tuple, np.ndarray)):
            inner = [x for v in values if v is not None for x in v]
            t: Any = {"type": "array", "items": _merged_primitive_type(inner)}
        elif isinstance(sample, dict):
            inner = [x for v in values if v for x in v.values()]
            t = {"type": "map", "values": _merged_primitive_type(inner)}
        else:
            t = _merged_primitive_type(values)
        if nullable:
            # Unions can't nest (spec): flatten a union column into one
            # union with a null branch rather than ["null", [...]]
            t = ["null"] + t if isinstance(t, list) else ["null", t]
        fields.append({"name": k, "type": t})
    return {"type": "record", "name": name, "fields": fields}
