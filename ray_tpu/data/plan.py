"""Logical plan + optimizer for datasets.

Reference analog: python/ray/data/_internal/logical/ — operators plus rules
(operator_fusion.py, limit_pushdown.py). Plans here are linear chains of
operators over blocks; the optimizer fuses adjacent row/batch transforms into
one task stage (zero intermediate materialization) and pushes limits into
reads.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional


class LogicalOp:
    name = "op"


@dataclasses.dataclass
class Read(LogicalOp):
    datasource: Any               # Datasource
    parallelism: int
    limit: Optional[int] = None
    name = "Read"


@dataclasses.dataclass
class MapBatches(LogicalOp):
    fn: Callable
    batch_size: Optional[int] = None
    fn_kwargs: Optional[dict] = None
    # "tasks" (stateless, fusable) or "actors" (stateful pool — expensive
    # setup amortized across blocks; ActorPoolMapOperator analog,
    # map_operator.py:34). fn may be a class: instantiated once per actor.
    compute: str = "tasks"
    concurrency: int = 2
    name = "MapBatches"


@dataclasses.dataclass
class MapRows(LogicalOp):
    fn: Callable
    name = "MapRows"


@dataclasses.dataclass
class FilterRows(LogicalOp):
    fn: Callable
    name = "Filter"


@dataclasses.dataclass
class FlatMap(LogicalOp):
    fn: Callable
    name = "FlatMap"


@dataclasses.dataclass
class Limit(LogicalOp):
    n: int
    name = "Limit"


@dataclasses.dataclass
class Repartition(LogicalOp):
    num_blocks: int
    name = "Repartition"


@dataclasses.dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None
    name = "RandomShuffle"


@dataclasses.dataclass
class Sort(LogicalOp):
    key: str
    descending: bool = False
    name = "Sort"


FUSABLE = (MapBatches, MapRows, FilterRows, FlatMap)


@dataclasses.dataclass
class FusedMap(LogicalOp):
    """A chain of row/batch transforms executed in one task."""

    stages: List[LogicalOp]
    name = "FusedMap"


def optimize(ops: List[LogicalOp]) -> List[LogicalOp]:
    """Fusion + limit pushdown."""
    # Limit pushdown: Limit directly after Read folds into the read.
    out: List[LogicalOp] = []
    for op in ops:
        if isinstance(op, Limit) and out and isinstance(out[-1], Read) \
                and out[-1].limit is None:
            out[-1] = dataclasses.replace(out[-1], limit=op.n)
        else:
            out.append(op)
    # Fuse adjacent map-like ops (actor-pool maps are their own stage).
    fused: List[LogicalOp] = []
    for op in out:
        if isinstance(op, FUSABLE) and not (
                isinstance(op, MapBatches) and op.compute == "actors"):
            if fused and isinstance(fused[-1], FusedMap):
                fused[-1].stages.append(op)
            else:
                fused.append(FusedMap(stages=[op]))
        else:
            fused.append(op)
    return fused
