"""Streaming execution of dataset plans over the task runtime.

Reference analog: python/ray/data/_internal/execution/streaming_executor.py:48
(run:231; scheduling loop streaming_executor_state.py:393/:531) and the
shuffle operators under _internal/execution/operators/. Blocks flow through
stages as OBJECT REFS — the driver never materializes intermediate data:

  * fused map stages run as remote tasks (bounded in-flight backpressure);
  * actor-pool map stages route blocks round-robin over stateful actors
    (ActorPoolMapOperator analog, map_operator.py:34);
  * barrier ops (random_shuffle / sort / repartition) run as distributed
    map/reduce task waves exchanging partitions through the object store —
    no driver materialization (the round-1 implementation pulled every
    block to the driver).

Only the final consumer (iter_batches / take) fetches block values.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data import plan as plan_mod
from ray_tpu.data.block import Block, BlockAccessor, block_from_batch

from ray_tpu.config import cfg


# ----------------------------------------------------------------- stats

def _block_meta(block: Block) -> dict:
    return {"rows": block.num_rows, "bytes": block.nbytes}


class DatasetStats:
    """Per-operator execution stats (reference analog:
    python/ray/data/_internal/stats.py — `Dataset.stats()`). Map stages
    return (block, meta) pairs with num_returns=2 so per-block rows/bytes
    ride tiny side objects instead of pulling blocks to the driver; wall
    time is measured driver-side per stage generator."""

    def __init__(self):
        self.stages: List[dict] = []

    def stage(self, name: str) -> dict:
        entry = {"name": name, "wall_s": 0.0, "blocks": 0,
                 "rows": None, "bytes": None, "_meta_refs": []}
        self.stages.append(entry)
        return entry

    def finalize(self):
        """Fold any meta refs not yet harvested incrementally. Stages fed
        by `_note_meta` (every streaming stage) have an empty `_meta_refs`
        list by the time the stream ends, so this adds NO tail stall —
        the old implementation blocked the consumer on a bulk
        `ray_tpu.get` of every per-block meta at stream end."""
        for s in self.stages:
            refs = s.pop("_meta_refs", [])
            if refs:
                metas = ray_tpu.get(refs, timeout=600)
                s["rows"] = (s["rows"] or 0) + sum(m["rows"] for m in metas)
                s["bytes"] = (s["bytes"] or 0) + sum(m["bytes"] for m in metas)
        return self

    def summary(self) -> str:
        lines = ["Operator statistics (per executed stage):"]
        for s in self.stages:
            extra = ""
            if s["rows"] is not None:
                extra = f", {s['rows']} rows, {s['bytes'] / 1e6:.2f} MB"
            lines.append(f"  {s['name']}: {s['wall_s'] * 1000:.0f}ms wall, "
                         f"{s['blocks']} blocks{extra}")
        return "\n".join(lines)


def _note_meta(stage_entry: Optional[dict], meta_ref) -> None:
    """Harvest one block's meta at block-completion time. The meta ref is
    sealed by the same task (num_returns=2) that sealed the block ref, so
    this get returns immediately — stats accumulate as the stream flows
    instead of stalling the consumer at stream end."""
    if stage_entry is None:
        return
    try:
        meta = ray_tpu.get(meta_ref, timeout=cfg().data_task_timeout_s)
    except Exception:
        return
    stage_entry["rows"] = (stage_entry["rows"] or 0) + meta["rows"]
    stage_entry["bytes"] = (stage_entry["bytes"] or 0) + meta["bytes"]


def _timed(stage_entry: Optional[dict], stream):
    """Wrap an (idx, ref) stream, accumulating wall time + block count."""
    if stage_entry is None:
        yield from stream
        return
    t0 = time.perf_counter()
    for item in stream:
        stage_entry["blocks"] += 1
        stage_entry["wall_s"] = time.perf_counter() - t0
        yield item


def _apply_fused(stages_payload: bytes, block: Block) -> Block:
    """Worker-side: run a fused chain of transforms on one block."""
    import cloudpickle

    from ray_tpu.data import plan as plan_mod
    from ray_tpu.data.block import BlockAccessor, block_from_batch, block_from_rows

    stages = cloudpickle.loads(stages_payload)
    for stage in stages:
        acc = BlockAccessor(block)
        if isinstance(stage, plan_mod.MapBatches):
            batch = acc.to_batch()
            out = stage.fn(batch, **(stage.fn_kwargs or {}))
            block = block_from_batch(out)
        elif isinstance(stage, plan_mod.MapRows):
            block = block_from_rows([stage.fn(r) for r in acc.to_rows()])
        elif isinstance(stage, plan_mod.FlatMap):
            rows = []
            for r in acc.to_rows():
                rows.extend(stage.fn(r))
            block = block_from_rows(rows)
        elif isinstance(stage, plan_mod.FilterRows):
            block = block_from_rows([r for r in acc.to_rows() if stage.fn(r)])
        else:
            raise TypeError(f"unfusable stage {stage}")
    return block


class _MapBatchActor:
    """Actor-pool map worker: holds the (possibly class-based) transform."""

    def __init__(self, payload: bytes):
        import cloudpickle

        op: plan_mod.MapBatches = cloudpickle.loads(payload)
        fn = op.fn
        self.fn = fn() if isinstance(fn, type) else fn
        self.kwargs = op.fn_kwargs or {}

    def transform(self, block: Block):
        batch = BlockAccessor(block).to_batch()
        out = block_from_batch(self.fn(batch, **self.kwargs))
        return out, _block_meta(out)


# ------------------------------------------------------------- ref streams
#
# A "ref stream" is an iterator of (index, ObjectRef-of-Block); stages
# compose as generator transformers with their own bounded in-flight sets.

def _ordered(pairs: Iterator[Tuple[int, object]]) -> Iterator[object]:
    buffered = {}
    next_idx = 0
    for idx, ref in pairs:
        buffered[idx] = ref
        while next_idx in buffered:
            yield buffered.pop(next_idx)
            next_idx += 1
    while buffered:
        yield buffered.pop(next_idx)
        next_idx += 1


def _wait_one(pending: dict):
    ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                            timeout=cfg().data_task_timeout_s)
    if not ready:
        raise TimeoutError("dataset task timed out")
    return ready


def _task_stage(upstream, payload: bytes, max_in_flight: int,
                stage_entry: Optional[dict] = None):
    @ray_tpu.remote(num_returns=2)
    def apply(block):
        out = _apply_fused(payload, block)
        return out, _block_meta(out)

    pending = {}
    for idx, ref in upstream:
        block_ref, meta_ref = apply.remote(ref)
        pending[block_ref] = (idx, meta_ref)
        while len(pending) >= max_in_flight:
            for r in _wait_one(pending):
                out_idx, m = pending.pop(r)
                _note_meta(stage_entry, m)
                yield out_idx, r
    while pending:
        for r in _wait_one(pending):
            out_idx, m = pending.pop(r)
            _note_meta(stage_entry, m)
            yield out_idx, r


def _actor_stage(upstream, op: plan_mod.MapBatches,
                 stage_entry: Optional[dict] = None):
    import cloudpickle

    Actor = ray_tpu.remote(_MapBatchActor)
    payload = cloudpickle.dumps(op)
    pool = [Actor.options(max_concurrency=2).remote(payload)
            for _ in range(max(1, op.concurrency))]
    pending = {}
    i = 0
    try:
        for idx, ref in upstream:
            actor = pool[i % len(pool)]
            i += 1
            block_ref, meta_ref = actor.transform.options(
                num_returns=2).remote(ref)
            pending[block_ref] = (idx, meta_ref)
            while len(pending) >= 2 * len(pool):
                for r in _wait_one(pending):
                    out_idx, m = pending.pop(r)
                    _note_meta(stage_entry, m)
                    yield out_idx, r
        while pending:
            for r in _wait_one(pending):
                out_idx, m = pending.pop(r)
                _note_meta(stage_entry, m)
                yield out_idx, r
    finally:
        # Runs on normal completion AND when the consumer stops early
        # (GeneratorExit) — pool actors must never outlive the stage.
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


# -------------------------------------------------- distributed barrier ops

def _count_rows(block: Block) -> int:
    return block.num_rows


def _gather_slices(specs, *blocks) -> Block:
    """Reduce side of repartition/limit: concat slices of input blocks."""
    parts = [BlockAccessor(blocks[i]).slice(lo, hi) for i, lo, hi in specs]
    return BlockAccessor.concat(parts)


def _split_random(block: Block, k: int, seed) -> List[Block]:
    rng = np.random.default_rng(seed)
    n = block.num_rows
    assign = rng.integers(0, k, n)
    return [block.take(np.nonzero(assign == j)[0]) for j in range(k)]


def _concat_shuffle(seed, *parts) -> Block:
    whole = BlockAccessor.concat(list(parts))
    rng = np.random.default_rng(seed)
    return whole.take(rng.permutation(whole.num_rows))


def _sample_keys(block: Block, key: str, n: int):
    col = block.column(key).to_numpy(zero_copy_only=False)
    if len(col) == 0:
        return col
    idx = np.random.default_rng(0).integers(0, len(col), min(n, len(col)))
    return col[idx]


def _split_range(block: Block, key: str, bounds) -> List[Block]:
    col = block.column(key).to_numpy(zero_copy_only=False)
    assign = np.searchsorted(bounds, col, side="right")
    return [block.take(np.nonzero(assign == j)[0])
            for j in range(len(bounds) + 1)]


def _concat_sort(key: str, descending: bool, *parts) -> Block:
    import pyarrow.compute as pc

    whole = BlockAccessor.concat(list(parts))
    order = "descending" if descending else "ascending"
    return whole.take(pc.sort_indices(whole, sort_keys=[(key, order)]))


def _shuffle_exchange(refs: List, split_fn, concat_fn, k: int,
                      split_args: Callable[[int], tuple],
                      concat_args: Callable[[int], tuple]) -> List:
    """Generic all-to-all: map each block into k partitions (num_returns=k),
    then one reduce task per partition concatenates its column. The object
    store carries every partition; the driver only routes refs."""
    split = ray_tpu.remote(split_fn)
    concat = ray_tpu.remote(concat_fn)
    if k == 1:
        # Degenerate exchange: a single reduce over all inputs.
        return [concat.remote(*concat_args(0), *refs)]
    parts = []
    for i, ref in enumerate(refs):
        out = split.options(num_returns=k).remote(ref, *split_args(i))
        parts.append(out)
    return [concat.remote(*concat_args(j), *[row[j] for row in parts])
            for j in range(k)]


def _apply_barrier_distributed(op, refs: List) -> List:
    """Barrier ops over block REFS -> block refs, as remote task waves."""
    count = ray_tpu.remote(_count_rows)
    if isinstance(op, plan_mod.Limit):
        counts = ray_tpu.get([count.remote(r) for r in refs], timeout=600)
        gather = ray_tpu.remote(_gather_slices)
        out, taken = [], 0
        for i, (ref, n) in enumerate(zip(refs, counts)):
            if taken >= op.n:
                break
            take = min(n, op.n - taken)
            out.append(gather.remote([(0, 0, take)], ref) if take < n else ref)
            taken += take
        return out
    if isinstance(op, plan_mod.Repartition):
        counts = ray_tpu.get([count.remote(r) for r in refs], timeout=600)
        total = sum(counts)
        k = max(1, op.num_blocks)
        per = (total + k - 1) // k
        # Output j covers global rows [j*per, min((j+1)*per, total)).
        starts = np.concatenate([[0], np.cumsum(counts)])
        gather = ray_tpu.remote(_gather_slices)
        out = []
        for j in range(k):
            lo, hi = j * per, min((j + 1) * per, total)
            if lo >= hi:
                break
            specs, needed = [], []
            for i, n in enumerate(counts):
                s, e = max(lo, starts[i]), min(hi, starts[i + 1])
                if s < e:
                    specs.append((len(needed), int(s - starts[i]),
                                  int(e - starts[i])))
                    needed.append(refs[i])
            out.append(gather.remote(specs, *needed))
        return out
    if isinstance(op, plan_mod.RandomShuffle):
        k = max(1, len(refs))
        base = op.seed if op.seed is not None else 0xC0FFEE
        return _shuffle_exchange(
            refs, _split_random, _concat_shuffle, k,
            split_args=lambda i: (k, base + i),
            concat_args=lambda j: (base + 7919 * (j + 1),))
    if isinstance(op, plan_mod.Sort):
        k = max(1, len(refs))
        sample = ray_tpu.remote(_sample_keys)
        samples = ray_tpu.get(
            [sample.remote(r, op.key, 32) for r in refs], timeout=600)
        allkeys = np.sort(np.concatenate([s for s in samples if len(s)]))
        if len(allkeys) == 0 or k == 1:
            bounds = np.array([])
            k = 1
        else:
            qs = [int(len(allkeys) * j / k) for j in range(1, k)]
            bounds = allkeys[qs]
        if op.descending:
            # Range-partition ascending, reduce sorts desc, reverse ranges.
            out = _shuffle_exchange(
                refs, _split_range, _concat_sort, len(bounds) + 1,
                split_args=lambda i: (op.key, bounds),
                concat_args=lambda j: (op.key, True))
            return out[::-1]
        return _shuffle_exchange(
            refs, _split_range, _concat_sort, len(bounds) + 1,
            split_args=lambda i: (op.key, bounds),
            concat_args=lambda j: (op.key, False))
    if isinstance(op, plan_mod.FusedMap):
        import cloudpickle

        payload = cloudpickle.dumps(op.stages)
        apply = ray_tpu.remote(_apply_fused)
        return [apply.remote(payload, r) for r in refs]
    if isinstance(op, plan_mod.MapBatches) and op.compute == "actors":
        # _actor_stage yields in COMPLETION order; restore index order so a
        # sorted/ordered upstream stays ordered.
        return list(_ordered(
            _actor_stage(((i, r) for i, r in enumerate(refs)), op)))
    raise TypeError(f"unknown barrier op {op}")


# ----------------------------------------------------------------- executor

_throttled = False   # current backpressure state (edge-counted metric)


def _effective_inflight(max_in_flight: int) -> int:
    """Resource-managed backpressure (streaming_executor_state.py:531 /
    backpressure_policy/ analog): the count cap shrinks as the LOCAL object
    store fills, so a fast producer can't drive the store into eviction/
    spill churn faster than consumers drain it. Never 0: spilling happens
    only at object-create time (spill.py create_with_spill), so at least
    one in-flight task must keep running to relieve pressure — a zero cap
    would livelock a barrier plan that pins its produced refs."""
    global _throttled
    from ray_tpu.core.worker import global_worker
    from ray_tpu.runtime import metric_defs

    try:
        store = global_worker().store
        if store is None or store.capacity <= 0:
            return max_in_flight
        pressure = store.used / store.capacity
    except Exception:
        return max_in_flight
    throttle = pressure >= cfg().data_store_highwater
    if throttle and not _throttled:
        metric_defs.DATA_BACKPRESSURE.inc()   # count transitions, not polls
    _throttled = throttle
    return max(1, max_in_flight // 4) if throttle else max_in_flight


def _streamable_tail(ops: List[plan_mod.LogicalOp]) -> bool:
    """True when every op after Read streams 1:1 over blocks (no barrier)."""
    for op in ops[1:]:
        if not (isinstance(op, plan_mod.FusedMap) or
                (isinstance(op, plan_mod.MapBatches)
                 and op.compute == "actors")):
            return False
    return True


def plan_block_count(ops: List[plan_mod.LogicalOp],
                     parallelism: int) -> Optional[int]:
    """Output block count of a barrier-free plan, known WITHOUT executing
    it (read tasks map 1:1 onto output blocks through fused/actor map
    stages). None for barrier plans (shuffle/sort/repartition/limit change
    the block count) — the streaming layer then has to materialize refs
    to learn the epoch size."""
    ops = plan_mod.optimize(ops)
    if not ops or not isinstance(ops[0], plan_mod.Read):
        return None
    if not _streamable_tail(ops):
        return None
    read: plan_mod.Read = ops[0]
    return len(read.datasource.read_tasks(parallelism, read.limit))


def execute_refs(ops: List[plan_mod.LogicalOp], parallelism: int,
                 max_in_flight: Optional[int] = None,
                 stats: Optional[DatasetStats] = None,
                 task_order: Optional[List[int]] = None) -> Iterator:
    """Run the optimized plan; yields BLOCK REFS in order as they complete
    (streaming until the first barrier op, task waves after).

    `task_order` permutes READ-TASK submission order: output index i is
    read task task_order[i], so for barrier-free plans the yielded block
    order IS the permutation — the seeded per-epoch shuffle of the
    streaming data plane, decided before any task runs (no extra pass
    over the data). Ignored for barrier plans (the barrier re-keys block
    order; callers permute the materialized ref list instead)."""
    import cloudpickle as cp

    if max_in_flight is None:
        max_in_flight = cfg().data_max_in_flight
    ops = plan_mod.optimize(ops)
    assert ops and isinstance(ops[0], plan_mod.Read), "plan must start with Read"
    read: plan_mod.Read = ops[0]
    rest = ops[1:]

    # Streamable prefix: fused task maps + actor-pool maps, until the first
    # barrier op (shuffle/sort/repartition/limit need all blocks).
    stream_stages: List[plan_mod.LogicalOp] = []
    barrier_ops: List[plan_mod.LogicalOp] = []
    for op in rest:
        streamable = isinstance(op, plan_mod.FusedMap) or (
            isinstance(op, plan_mod.MapBatches) and op.compute == "actors")
        if streamable and not barrier_ops:
            stream_stages.append(op)
        else:
            barrier_ops.append(op)

    tasks = read.datasource.read_tasks(parallelism, read.limit)

    # Fold the read plus any LEADING fused task stages into one task.
    lead_payloads = []
    while stream_stages and isinstance(stream_stages[0], plan_mod.FusedMap):
        lead_payloads.append(cp.dumps(stream_stages.pop(0).stages))

    @ray_tpu.remote(num_returns=2)
    def run_block(read_task_payload, payloads):
        read_task = cp.loads(read_task_payload)
        block = read_task()
        for p in payloads:
            block = _apply_fused(p, block)
        return block, _block_meta(block)

    read_entry = None
    if stats is not None:
        name = type(read.datasource).__name__
        if lead_payloads:
            name += f"+{len(lead_payloads)} fused map(s)"
        read_entry = stats.stage(f"Read[{name}]")

    order = list(range(len(tasks)))
    if task_order is not None and not barrier_ops:
        if sorted(task_order) != order:
            raise ValueError("task_order must be a permutation of "
                             f"range({len(tasks)})")
        order = list(task_order)

    def source():
        pending = {}
        queue = [(i, cp.dumps(tasks[t])) for i, t in enumerate(order)]
        while queue or pending:
            while queue and len(pending) < _effective_inflight(max_in_flight):
                idx, payload = queue.pop(0)
                block_ref, meta_ref = run_block.remote(payload, lead_payloads)
                pending[block_ref] = (idx, meta_ref)
            ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                    timeout=cfg().data_task_timeout_s)
            if not ready:
                raise TimeoutError("dataset task timed out")
            for ref in ready:
                idx, meta_ref = pending.pop(ref)
                _note_meta(read_entry, meta_ref)
                yield idx, ref

    stream = _timed(read_entry, source())
    for op in stream_stages:
        entry = None
        if isinstance(op, plan_mod.FusedMap):
            if stats is not None:
                entry = stats.stage(f"Map[{len(op.stages)} fused]")
            stream = _task_stage(stream, cp.dumps(op.stages), max_in_flight,
                                 entry)
        else:
            if stats is not None:
                entry = stats.stage(f"MapBatches[actors x{op.concurrency}]")
            stream = _actor_stage(stream, op, entry)
        stream = _timed(entry, stream)

    if not barrier_ops:
        yield from _ordered(stream)
        return
    refs = list(_ordered(stream))
    for op in barrier_ops:
        t0 = time.perf_counter()
        refs = _apply_barrier_distributed(op, refs)
        if stats is not None:
            entry = stats.stage(type(op).__name__)
            entry["wall_s"] = time.perf_counter() - t0
            entry["blocks"] = len(refs)
    yield from refs


def execute_streaming(ops: List[plan_mod.LogicalOp], parallelism: int,
                      max_in_flight: Optional[int] = None,
                      stats: Optional[DatasetStats] = None) -> Iterator[Block]:
    """Run the plan; yields materialized output blocks (final consumer)."""
    for ref in execute_refs(ops, parallelism, max_in_flight, stats):
        yield ray_tpu.get(ref, timeout=600)