"""Streaming execution of dataset plans over the task runtime.

Reference analog: python/ray/data/_internal/execution/streaming_executor.py:48
(run:231; scheduling loop streaming_executor_state.py:393/:531). Blocks flow
through fused map stages as remote tasks with bounded in-flight concurrency
(backpressure); results stream to the consumer as they finish rather than
materializing the whole dataset.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import plan as plan_mod
from ray_tpu.data.block import Block, BlockAccessor, block_from_batch

MAX_IN_FLIGHT = 8


def _apply_fused(stages_payload: bytes, block: Block) -> Block:
    """Worker-side: run a fused chain of transforms on one block."""
    import cloudpickle

    from ray_tpu.data import plan as plan_mod
    from ray_tpu.data.block import BlockAccessor, block_from_batch, block_from_rows

    stages = cloudpickle.loads(stages_payload)
    for stage in stages:
        acc = BlockAccessor(block)
        if isinstance(stage, plan_mod.MapBatches):
            batch = acc.to_batch()
            out = stage.fn(batch, **(stage.fn_kwargs or {}))
            block = block_from_batch(out)
        elif isinstance(stage, plan_mod.MapRows):
            block = block_from_rows([stage.fn(r) for r in acc.to_rows()])
        elif isinstance(stage, plan_mod.FlatMap):
            rows = []
            for r in acc.to_rows():
                rows.extend(stage.fn(r))
            block = block_from_rows(rows)
        elif isinstance(stage, plan_mod.FilterRows):
            block = block_from_rows([r for r in acc.to_rows() if stage.fn(r)])
        else:
            raise TypeError(f"unfusable stage {stage}")
    return block


def execute_streaming(ops: List[plan_mod.LogicalOp], parallelism: int,
                      max_in_flight: int = MAX_IN_FLIGHT) -> Iterator[Block]:
    """Run the optimized plan; yields output blocks as they complete."""
    import cloudpickle

    ops = plan_mod.optimize(ops)
    assert ops and isinstance(ops[0], plan_mod.Read), "plan must start with Read"
    read: plan_mod.Read = ops[0]
    rest = ops[1:]

    # Split plan into streamable prefix (fused maps) and barrier suffix
    # (repartition/shuffle/sort/limit need all blocks).
    stream_stages: List[plan_mod.FusedMap] = []
    barrier_ops: List[plan_mod.LogicalOp] = []
    for op in rest:
        if isinstance(op, plan_mod.FusedMap) and not barrier_ops:
            stream_stages.append(op)
        else:
            barrier_ops.append(op)

    tasks = read.datasource.read_tasks(parallelism, read.limit)

    fused_payloads = [cloudpickle.dumps(s.stages) for s in stream_stages]

    @ray_tpu.remote
    def run_block(read_task_payload, payloads):
        import cloudpickle as cp

        read_task = cp.loads(read_task_payload)
        block = read_task()
        for p in payloads:
            block = _apply_fused(p, block)
        return block

    import cloudpickle as cp

    # Bounded-in-flight dispatch with order preservation: tasks complete in
    # any order, blocks are yielded in plan order (backpressure loop,
    # select_operator_to_run analog).
    queue = [(i, cp.dumps(t)) for i, t in enumerate(tasks)]
    pending: dict = {}         # ref -> index
    completed: dict = {}       # index -> Block
    next_idx = 0

    def submit_more():
        while queue and len(pending) < max_in_flight:
            idx, payload = queue.pop(0)
            pending[run_block.remote(payload, fused_payloads)] = idx

    def stream():
        nonlocal next_idx
        submit_more()
        while pending or completed:
            while next_idx in completed:
                yield completed.pop(next_idx)
                next_idx += 1
            if not pending:
                continue
            ready, _ = ray_tpu.wait(list(pending), num_returns=1, timeout=600)
            if not ready:
                raise TimeoutError("dataset task timed out")
            for ref in ready:
                idx = pending.pop(ref)
                completed[idx] = ray_tpu.get(ref, timeout=600)
            submit_more()

    if not barrier_ops:
        yield from stream()
        return

    # Barrier path: materialize, then apply barrier ops locally (distributed
    # shuffle lands in a later round).
    blocks = list(stream())
    for op in barrier_ops:
        blocks = _apply_barrier(op, blocks)
    yield from blocks


def _apply_barrier(op, blocks: List[Block]) -> List[Block]:
    from ray_tpu.data.block import BlockAccessor

    if isinstance(op, plan_mod.Limit):
        out, taken = [], 0
        for b in blocks:
            if taken >= op.n:
                break
            take = min(b.num_rows, op.n - taken)
            out.append(BlockAccessor(b).slice(0, take))
            taken += take
        return out
    if isinstance(op, plan_mod.Repartition):
        whole = BlockAccessor.concat(blocks)
        n = whole.num_rows
        k = max(1, op.num_blocks)
        per = (n + k - 1) // k
        return [BlockAccessor(whole).slice(i * per, min((i + 1) * per, n))
                for i in range(k) if i * per < n]
    if isinstance(op, plan_mod.RandomShuffle):
        whole = BlockAccessor.concat(blocks)
        rng = np.random.default_rng(op.seed)
        idx = rng.permutation(whole.num_rows)
        import pyarrow.compute as pc

        return [whole.take(idx)]
    if isinstance(op, plan_mod.Sort):
        whole = BlockAccessor.concat(blocks)
        import pyarrow.compute as pc

        order = "descending" if op.descending else "ascending"
        idx = pc.sort_indices(whole, sort_keys=[(op.key, order)])
        return [whole.take(idx)]
    if isinstance(op, plan_mod.FusedMap):
        # FusedMap after a barrier op: run locally.
        import cloudpickle

        payload = cloudpickle.dumps(op.stages)
        return [_apply_fused(payload, b) for b in blocks]
    raise TypeError(f"unknown barrier op {op}")
