"""crc32c (Castagnoli) for TFRecord framing.

The reference delegates TFRecord CRCs to TensorFlow / crc32c wheels; a
per-byte Python loop caps ingest at ~10-20 MB/s, so the hot path is a
30-line C helper compiled on demand (same pattern as the native object
store, runtime/object_store/build.py). Falls back to slicing-by-8 pure
Python when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_crc32c.c")
# NOT "_crc32c.so": an extension-suffixed file with the module's own name
# would shadow this .py module on import (PyInit_ lookup failure).
_SO = os.path.join(_DIR, "libcrc32c.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_native_failed = False

_C_SOURCE = r"""
#include <stdint.h>
#include <stddef.h>

static uint32_t table[8][256];
static int ready = 0;

static void init_tables(void) {
  for (int i = 0; i < 256; i++) {
    uint32_t c = (uint32_t)i;
    for (int k = 0; k < 8; k++) c = (c >> 1) ^ ((c & 1) ? 0x82F63B78u : 0);
    table[0][i] = c;
  }
  for (int t = 1; t < 8; t++)
    for (int i = 0; i < 256; i++)
      table[t][i] = (table[t-1][i] >> 8) ^ table[0][table[t-1][i] & 0xFF];
  ready = 1;
}

uint32_t crc32c(const uint8_t* p, size_t n) {
  if (!ready) init_tables();
  uint32_t crc = 0xFFFFFFFFu;
  while (n >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8)
         | ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
    crc = table[7][crc & 0xFF] ^ table[6][(crc >> 8) & 0xFF]
        ^ table[5][(crc >> 16) & 0xFF] ^ table[4][crc >> 24]
        ^ table[3][p[4]] ^ table[2][p[5]] ^ table[1][p[6]] ^ table[0][p[7]];
    p += 8; n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ table[0][(crc ^ *p++) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}
"""


def _ensure_native() -> Optional[ctypes.CDLL]:
    global _lib, _native_failed
    if _lib is not None or _native_failed:
        return _lib
    with _lock:
        if _lib is not None or _native_failed:
            return _lib
        try:
            if not os.path.exists(_SRC):
                with open(_SRC, "w") as f:
                    f.write(_C_SOURCE)
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                tmp = f"{_SO}.{os.getpid()}.tmp"
                subprocess.run(
                    ["cc", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, _SO)
            lib = ctypes.CDLL(_SO)
            lib.crc32c.restype = ctypes.c_uint32
            lib.crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
            _lib = lib
        except Exception:
            _native_failed = True
    return _lib


# Pure-Python fallback table (single table; loop is only used without cc).
_TABLE: List[int] = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _TABLE.append(_c)


def crc32c(data: bytes) -> int:
    lib = _ensure_native()
    if lib is not None:
        return lib.crc32c(data, len(data))
    crc = 0xFFFFFFFF
    table = _TABLE
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF
