"""Streaming data plane: pipelined, backpressured train ingestion.

Reference analogs: tf.data's `prefetch()` overlap and Ray Data's streaming
executor + `Dataset.streaming_split` (python/ray/data/iterator.py,
_internal/execution/streaming_executor.py). The batch-shaped path drives the
plan synchronously from the consumer, so every train step pays
read + transform + host->device transfer on the critical path. This module
turns it into a push-based pipeline:

  * `StreamingIterator` — a producer THREAD drives the plan's bounded
    in-flight ref stream (execution.py) and pushes ready batches through a
    `DeviceChannel` ring; `next(it)` is a ring pop when the pipeline keeps
    up. A semaphore caps produced-but-unconsumed batches at
    `prefetch_batches`, so a slow consumer backpressures the whole pipeline
    (the stage-level in-flight caps bound the rest).
  * Zero-pickle last hop — steady-state batches ride the ring as one
    `_FAST_DEVICE` frame PER COLUMN (jax arrays move as raw dlpack bytes,
    serialization.py), landing on the consumer's device via the channel's
    `device_index`. Schema frames (pickled name lists) flow only when the
    column set changes — once per stream in practice.
  * `StreamShard` / `Dataset.streaming_split(n)` — one `_StreamCoordinator`
    actor runs the plan ONCE per epoch as a shared, seeded, pipelined ref
    stream; shard r consumes permuted positions r, r+n, r+2n, ... The
    permutation depends only on (seed, epoch), so same seed + world gives a
    bit-identical global visit order, and the coordinator holds REFS only —
    no driver materialization of data.
  * `StreamCursor` — (epoch, per-shard block offset, batch-in-block offset,
    seed), advanced at every pop. Batches never straddle block boundaries
    in streaming mode, so a checkpointed cursor resumes mid-epoch with the
    bit-identical remaining visit order. Train's `report(state=...)` saves
    cursors through the async checkpoint plane under the separate
    "datastream" manifest (train/session.py).

See docs/data_streaming.md for knobs, numbers, and cursor semantics.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.config import cfg
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.execution import (DatasetStats, execute_refs,
                                    plan_block_count)

__all__ = ["StreamCursor", "StreamingIterator", "StreamShard",
           "make_stream_shards", "shutdown_shards"]

_CURSOR_MANIFEST = "datastream"  # checkpoint-plane manifest name for cursors


# ----------------------------------------------------------------- cursor

@dataclasses.dataclass
class StreamCursor:
    """Resumable position of one consumer's stream. `block_offset` counts
    PER-SHARD blocks fully consumed this epoch; `batch_offset` counts
    batches already popped from the block at `block_offset`. Both advance
    consumer-side at pop time, so a cursor captured between two `next()`
    calls replays nothing and skips nothing."""

    epoch: int = 0
    block_offset: int = 0
    batch_offset: int = 0
    seed: int = 0

    def as_row(self) -> np.ndarray:
        return np.array([self.epoch, self.block_offset, self.batch_offset,
                         self.seed], dtype=np.int64)

    @classmethod
    def from_row(cls, row) -> "StreamCursor":
        row = np.asarray(row).reshape(-1)
        return cls(epoch=int(row[0]), block_offset=int(row[1]),
                   batch_offset=int(row[2]), seed=int(row[3]))


def _epoch_permutation(seed: int, epoch: int, n: int) -> List[int]:
    """The epoch's seeded visit order over n blocks. Depends only on
    (seed, epoch) — every shard of every attempt derives the same order."""
    rng = np.random.default_rng([int(seed) & 0xFFFFFFFF, int(epoch)])
    return [int(i) for i in rng.permutation(n)]


# ------------------------------------------------------------- transports
#
# The ring carries BATCHES between the producer thread and the consumer.
# Frame protocol over the DeviceChannel (deterministic framing — the reader
# always knows what the next frame is, no type sniffing in steady state):
#
#   [schema list]  only when the column set changed (pickled; rare)
#   header         int64 jax array [shard_block_idx, batch_idx, last, ncols]
#   column x ncols one _FAST_DEVICE frame per column (zero-pickle)
#
# Non-numeric batches (object/string columns) fall back to one
# (header, dict) tuple frame — a documented slow path.

def _as_device_array(v):
    """Numeric column -> jax array for the zero-pickle frame; None when
    the column can't move as raw bytes (object/string dtypes)."""
    try:
        a = np.asarray(v)
        if a.dtype.kind in "OUSV":
            return None
        import jax.numpy as jnp

        return jnp.asarray(a)
    except Exception:
        return None


class _ChannelRing:
    """SPSC batch transport over a DeviceChannel. The writer (producer
    thread) and reader (consumer) share this object in-process; writer
    state (`_schema`, `_wv`) and reader state (`_rschema`, `_rv`) are
    disjoint, so no lock is needed beyond the channel's own protocol."""

    def __init__(self, capacity_frames: int, device_index: Optional[int]):
        from ray_tpu.dag.device_channel import DeviceChannel

        self._ch = DeviceChannel(capacity=capacity_frames,
                                 device_index=device_index)
        self._schema: Optional[Tuple[str, ...]] = None   # writer side
        self._rschema: Tuple[str, ...] = ()              # reader side

    # -- writer (producer thread) ------------------------------------------
    def put(self, header: Tuple[int, int, int], batch: Dict[str, Any]) -> bool:
        """Push one batch; True when it rode the zero-pickle column path."""
        import jax.numpy as jnp

        cols: Optional[Dict[str, Any]] = {}
        for k, v in batch.items():
            arr = _as_device_array(v)
            if arr is None:
                cols = None
                break
            cols[k] = arr
        if cols is None:
            # Non-numeric batch: one pickled frame (documented slow path).
            self._ch.write((tuple(header), batch))
            return False
        names = tuple(cols)
        if names != self._schema:
            self._schema = names
            self._ch.write(list(names))
        self._ch.write(jnp.asarray([header[0], header[1], header[2],
                                    len(names)], dtype=jnp.int32))
        for k in names:
            self._ch.write(cols[k])
        return True

    def close_write(self) -> None:
        self._ch.close_write()

    # -- reader (consumer) -------------------------------------------------
    def get(self, timeout: Optional[float] = None
            ) -> Tuple[Tuple[int, int, int], Dict[str, Any]]:
        frame = self._ch.read(timeout=timeout)   # ChannelClosed at stream end
        if isinstance(frame, list):
            self._rschema = tuple(frame)
            frame = self._ch.read(timeout=timeout)
        if isinstance(frame, tuple):
            header, batch = frame
            return (int(header[0]), int(header[1]), int(header[2])), batch
        h = np.asarray(frame)
        ncols = int(h[3])
        cols = [self._ch.read(timeout=timeout) for _ in range(ncols)]
        return ((int(h[0]), int(h[1]), int(h[2])),
                dict(zip(self._rschema, cols)))

    def close_read(self) -> None:
        try:
            self._ch.close_read()
        except Exception:
            pass

    def drain(self) -> None:
        try:
            self._ch.drain()
        except Exception:
            pass


class _QueueRing:
    """In-process fallback when there is no object store or no jax (plain
    library use outside a cluster). Hands batch dicts across the thread
    boundary directly — nothing serializes at all."""

    class Closed(Exception):
        pass

    _END = object()

    def __init__(self):
        import queue

        self._q: "queue.Queue" = queue.Queue()

    def put(self, header, batch) -> bool:
        self._q.put((tuple(header), batch))
        return True

    def close_write(self) -> None:
        self._q.put(self._END)

    def get(self, timeout: Optional[float] = None):
        item = self._q.get(timeout=timeout)
        if item is self._END:
            from ray_tpu.dag.channel import ChannelClosed

            raise ChannelClosed()
        return item

    def close_read(self) -> None:
        pass

    def drain(self) -> None:
        pass


def _make_ring(capacity_frames: int, device_index: Optional[int]):
    try:
        from ray_tpu.core import worker as worker_mod

        worker_mod.global_worker()._require_store()
        import jax  # noqa: F401

        return _ChannelRing(capacity_frames, device_index)
    except Exception:
        return _QueueRing()


# -------------------------------------------------------------- iterator

def _block_batches(block: Block, batch_size: Optional[int],
                   drop_last: bool) -> List[Dict[str, np.ndarray]]:
    """Split one block into host batches. Streaming batches never straddle
    block boundaries (unlike the batch-shaped `iter_batches` re-chunker):
    that makes (block_offset, batch_offset) cursors exact, at the cost of
    a short tail batch per block (dropped under drop_last)."""
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return []
    if batch_size is None:
        return [acc.to_batch()]
    out = []
    for lo in range(0, n, batch_size):
        hi = min(lo + batch_size, n)
        if drop_last and hi - lo < batch_size:
            break
        out.append(BlockAccessor(acc.slice(lo, hi)).to_batch())
    return out


class StreamingIterator:
    """Pipelined batch iterator: a daemon producer thread pulls blocks from
    `source(cursor)` (an iterator of (shard_block_index, Block) starting at
    the cursor), slices them into batches, and pushes them through the
    device ring; `__next__` pops. Blocking time in `__next__` is the true
    input-wait — it books the `input_wait` train-telemetry phase and the
    `ray_tpu_data_input_wait_ms` histogram.

    Backpressure: at most `prefetch_batches` produced-but-unconsumed
    batches exist at any moment (semaphore acquired before each push,
    released at each pop); upstream, the executor's bounded in-flight caps
    hold. `max_backlog` records the high-water mark as the proof probe.

    Adaptive depth: pass ``prefetch_batches="adaptive"`` and the window
    sizes itself from the same signal `ray_tpu_data_input_wait_ms`
    observes — a blocking pop grows the depth by one (an extra semaphore
    permit), a sustained quiet run shrinks it by withholding one release.
    Clamps: [1, RAY_TPU_DATA_PREFETCH_MAX] (default 16); the quiet window
    is RAY_TPU_DATA_PREFETCH_QUIET pops (default 32). The current depth is
    the `prefetch_depth` probe; `depth_grows`/`depth_shrinks` count the
    controller's moves."""

    def __init__(self, source: Callable[[StreamCursor], Iterator[
                     Tuple[int, Block]]], *,
                 batch_size: Optional[int] = 256,
                 batch_format: str = "numpy",
                 drop_last: bool = False,
                 prefetch_batches=2,
                 device_index: Optional[int] = None,
                 cursor: Optional[StreamCursor] = None,
                 on_exhausted: Optional[Callable[[], None]] = None):
        self._source = source
        self._batch_size = batch_size
        self._batch_format = batch_format
        self._drop_last = drop_last
        if prefetch_batches == "adaptive":
            self._adaptive = True
            self._min_prefetch = 1
            self._max_prefetch = max(2, int(os.environ.get(
                "RAY_TPU_DATA_PREFETCH_MAX", "16")))
            self._prefetch = min(2, self._max_prefetch)
        else:
            self._adaptive = False
            self._prefetch = max(1, int(prefetch_batches))
            self._min_prefetch = self._max_prefetch = self._prefetch
        self._quiet_window = max(1, int(os.environ.get(
            "RAY_TPU_DATA_PREFETCH_QUIET", "32")))
        self._quiet_run = 0
        self.depth_grows = 0
        self.depth_shrinks = 0
        self._on_exhausted = on_exhausted
        self.cursor = cursor if cursor is not None else StreamCursor()
        self._start = dataclasses.replace(self.cursor)
        # Frame capacity: a batch is 1 header + ncols frames. 8 columns per
        # batch fully buffered is generous; wider batches just make the
        # writer block mid-batch while the reader drains (no deadlock: the
        # reader never waits on anything but the channel). Sized for the
        # MAX depth so adaptive growth never outruns the ring.
        self._ring = _make_ring((self._max_prefetch + 2) * 8, device_index)
        self._sem = threading.Semaphore(self._prefetch)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._finished = False
        self._produced = 0
        self._consumed = 0
        # Probes: backpressure proof + prefetch effectiveness.
        self.max_backlog = 0
        self.pops = 0
        self.hits = 0          # pops that returned without blocking
        self.wait_s = 0.0      # total blocking input-wait
        self.zero_pickle_batches = 0
        self.fallback_batches = 0
        self._thread = threading.Thread(target=self._produce, daemon=True,
                                        name="data-stream-producer")
        self._thread.start()

    # -- producer thread ---------------------------------------------------
    def _produce(self) -> None:
        from ray_tpu.dag.channel import ChannelClosed
        from ray_tpu.runtime import metric_defs

        try:
            for s_idx, block in self._source(self._start):
                metric_defs.DATA_BLOCKS_PRODUCED.inc()
                batches = _block_batches(block, self._batch_size,
                                         self._drop_last)
                skip = (self._start.batch_offset
                        if s_idx == self._start.block_offset else 0)
                for j in range(skip, len(batches)):
                    while not self._sem.acquire(timeout=0.1):
                        if self._stop.is_set():
                            return
                    if self._stop.is_set():
                        return
                    header = (s_idx, j, 1 if j == len(batches) - 1 else 0)
                    if self._ring.put(header, batches[j]):
                        self.zero_pickle_batches += 1
                    else:
                        self.fallback_batches += 1
                    self._produced += 1
                    backlog = self._produced - self._consumed
                    if backlog > self.max_backlog:
                        self.max_backlog = backlog
                    metric_defs.DATA_BACKLOG_DEPTH.set(backlog)
            self._ring.close_write()
        except ChannelClosed:
            pass   # consumer abandoned the stream; nothing to flush
        except BaseException as e:  # noqa: BLE001 - re-raised at the consumer
            self._error = e
            try:
                self._ring.close_write()
            except Exception:
                pass

    # -- consumer ----------------------------------------------------------
    def __iter__(self) -> "StreamingIterator":
        return self

    def __next__(self):
        from ray_tpu.dag.channel import ChannelClosed
        from ray_tpu.runtime import metric_defs
        from ray_tpu.train.session import step_phase

        t0 = time.perf_counter()
        try:
            with step_phase("input_wait"):
                header, batch = self._ring.get(
                    timeout=cfg().data_task_timeout_s)
        except ChannelClosed:
            self._finish()
            raise StopIteration
        dt = time.perf_counter() - t0
        self.pops += 1
        self.wait_s += dt
        if dt < 1e-3:
            self.hits += 1
        metric_defs.DATA_INPUT_WAIT_MS.observe(dt * 1e3)
        self._consumed += 1
        metric_defs.DATA_BACKLOG_DEPTH.set(self._produced - self._consumed)
        for _ in range(self._adapt(dt) if self._adaptive else 1):
            self._sem.release()
        s_idx, j, last = header
        if last:
            self.cursor.block_offset = s_idx + 1
            self.cursor.batch_offset = 0
        else:
            self.cursor.block_offset = s_idx
            self.cursor.batch_offset = j + 1
        return self._format(batch)

    def _format(self, batch: Dict[str, Any]):
        if self._batch_format in ("jax", "device"):
            return batch
        if self._batch_format in ("numpy", "default"):
            return {k: np.asarray(v) for k, v in batch.items()}
        if self._batch_format == "pandas":
            import pandas as pd

            return pd.DataFrame({k: np.asarray(v) for k, v in batch.items()})
        raise ValueError(
            f"unknown streaming batch_format {self._batch_format!r} "
            "(numpy | jax | pandas)")

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self._thread.join(timeout=60)
        self._ring.drain()
        if self._error is not None:
            err, self._error = self._error, None
            raise err
        if self._on_exhausted is not None:
            self._on_exhausted()

    def stop(self) -> None:
        """Abandon the stream early: unwedge and join the producer."""
        self._stop.set()
        self._ring.close_read()
        self._thread.join(timeout=10)
        self._ring.drain()

    def __del__(self):
        try:
            if not self._finished and self._thread.is_alive():
                self.stop()
        except Exception:
            pass

    def _adapt(self, dt: float) -> int:
        """Adaptive-depth controller, run at every pop. Returns how many
        semaphore permits to release: 2 grows the window (the producer may
        now keep one more batch in flight), 1 holds it, 0 shrinks it by
        one. A blocking pop is direct evidence the producer fell behind;
        only a sustained run of non-blocking pops is evidence the window
        is oversized (a single fast pop proves nothing — the producer may
        just have gotten lucky)."""
        if dt >= 1e-3:
            self._quiet_run = 0
            if self._prefetch < self._max_prefetch:
                self._prefetch += 1
                self.depth_grows += 1
                return 2
            return 1
        self._quiet_run += 1
        if (self._quiet_run >= self._quiet_window
                and self._prefetch > self._min_prefetch):
            self._quiet_run = 0
            self._prefetch -= 1
            self.depth_shrinks += 1
            return 0
        return 1

    # -- probes ------------------------------------------------------------
    @property
    def prefetch_depth(self) -> int:
        """Current prefetch window (fixed unless "adaptive")."""
        return self._prefetch

    @property
    def prefetch_hit_rate(self) -> float:
        """Fraction of pops served without blocking — 1.0 means the
        pipeline fully hid ingestion behind the consumer's compute."""
        return self.hits / self.pops if self.pops else 0.0

    def state_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self.cursor)


# ------------------------------------------------------- shared execution

class _StreamCoordinator:
    """Driver-side actor producing ONE shared, seeded, pipelined block-ref
    stream per epoch; shards pull disjoint permuted positions on demand.
    Holds refs only (the object store holds the blocks), so a lagging rank
    costs ref-list memory, never driver data. Epochs older than the newest
    two are dropped, bounding that list across long runs."""

    def __init__(self, ops_payload: bytes, parallelism: int,
                 seed: Optional[int], world: int, equal: bool,
                 max_in_flight: Optional[int]):
        import cloudpickle

        # graftlint: allow[hot-pickle] plan arrives once at stream setup, never per block
        self._ops = cloudpickle.loads(ops_payload)
        self._parallelism = parallelism
        self._seed = seed
        self._world = max(1, int(world))
        self._equal = bool(equal)
        self._max_in_flight = max_in_flight
        self._epochs: Dict[int, dict] = {}
        self._total_hint = plan_block_count(self._ops, parallelism)

    def _epoch(self, epoch: int) -> dict:
        st = self._epochs.get(epoch)
        if st is not None:
            return st
        stats = DatasetStats()
        order = None
        if self._total_hint is not None and self._seed is not None:
            order = _epoch_permutation(self._seed, epoch, self._total_hint)
        gen = execute_refs(self._ops, self._parallelism,
                           max_in_flight=self._max_in_flight,
                           stats=stats, task_order=order)
        st = {"gen": gen, "refs": [], "done": False, "stats": stats}
        if self._total_hint is None:
            # Barrier plan: ref production is a task wave, not a stream —
            # drain it (refs only), then permute the materialized list so
            # the seeded epoch order still holds.
            refs = list(gen)
            if self._seed is not None:
                perm = _epoch_permutation(self._seed, epoch, len(refs))
                refs = [refs[i] for i in perm]
            st["refs"] = refs
            st["done"] = True
        self._epochs[epoch] = st
        for old in [e for e in self._epochs if e < epoch - 1]:
            del self._epochs[old]
        return st

    def next_block(self, epoch: int, pos: int):
        """The block ref at global permuted position `pos` of `epoch`, or
        None past the epoch's end. Under equal=True the tail remainder
        (total % world) is dropped so every shard sees the same block
        count; a position is only served once enough downstream blocks
        exist to prove it survives the truncation."""
        st = self._epoch(epoch)
        guard = self._world if (self._equal and self._world > 1) else 1
        while not st["done"] and len(st["refs"]) < pos + guard:
            try:
                st["refs"].append(next(st["gen"]))
            except StopIteration:
                st["done"] = True
        if len(st["refs"]) <= pos:
            return None
        if st["done"] and self._equal and self._world > 1:
            usable = len(st["refs"]) - len(st["refs"]) % self._world
            if pos >= usable:
                return None
        return st["refs"][pos]

    def epoch_stats(self, epoch: int) -> Optional[str]:
        st = self._epochs.get(epoch)
        return None if st is None else st["stats"].finalize().summary()


class StreamShard:
    """One consumer's handle onto a shared streaming execution. Picklable —
    it ships (coordinator handle, rank/world/seed, batch defaults) to a
    train worker; the iterator, its ring, and its producer thread are all
    created consumer-side at `iter_batches()` time.

    Epochs: each `iter_batches()` call streams ONE epoch (the shard's
    current one) and advances the cursor to the next epoch on exhaustion.
    `load_cursor()` / a restored checkpoint seeks mid-epoch; the epoch's
    pipeline replays up to the cursor without re-yielding consumed data,
    so the remaining visit order is bit-identical to the uninterrupted
    run."""

    def __init__(self, coordinator, rank: int, world: int,
                 seed: Optional[int], *, batch_size: Optional[int] = 256,
                 batch_format: str = "numpy", drop_last: bool = False,
                 prefetch_batches=2,
                 device_index: Optional[int] = None):
        self._coord = coordinator
        self.rank = int(rank)
        self.world = max(1, int(world))
        self.seed = seed
        self._defaults = dict(batch_size=batch_size,
                              batch_format=batch_format,
                              drop_last=drop_last,
                              prefetch_batches=prefetch_batches,
                              device_index=device_index)
        self._cursor = StreamCursor(seed=int(seed or 0))
        self._it: Optional[StreamingIterator] = None

    def __reduce__(self):
        return (_rebuild_shard, (self._coord, self.rank, self.world,
                                 self.seed, self._defaults,
                                 dataclasses.asdict(self._cursor)))

    # -- cursor ------------------------------------------------------------
    @property
    def cursor(self) -> StreamCursor:
        if self._it is not None and not self._it._finished:
            return self._it.cursor
        return self._cursor

    def state_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self.cursor)

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self._cursor = StreamCursor(**{k: int(v) for k, v in state.items()})
        self._it = None

    def cursor_row(self) -> np.ndarray:
        return self.cursor.as_row()

    def load_cursor(self, row) -> None:
        self._cursor = StreamCursor.from_row(row)
        self._it = None

    # -- consumption -------------------------------------------------------
    def _source(self, cursor: StreamCursor) -> Iterator[Tuple[int, Block]]:
        timeout = cfg().data_task_timeout_s
        pos = cursor.block_offset
        while True:
            ref = ray_tpu.get(
                self._coord.next_block.remote(
                    cursor.epoch, self.rank + pos * self.world),
                timeout=timeout)
            if ref is None:
                return
            yield pos, ray_tpu.get(ref, timeout=timeout)
            pos += 1

    def iter_batches(self, **overrides) -> StreamingIterator:
        kw = {**self._defaults, **overrides}
        start = dataclasses.replace(self._cursor)

        def on_exhausted():
            self._cursor = StreamCursor(epoch=start.epoch + 1,
                                        seed=int(self.seed or 0))

        it = StreamingIterator(self._source, cursor=start,
                               on_exhausted=on_exhausted, **kw)
        self._it = it
        return it

    def stats(self, epoch: Optional[int] = None) -> Optional[str]:
        """Per-epoch execution stats from the shared coordinator."""
        e = self.cursor.epoch if epoch is None else epoch
        return ray_tpu.get(self._coord.epoch_stats.remote(e), timeout=60)


def _rebuild_shard(coord, rank, world, seed, defaults, cursor_state):
    shard = StreamShard(coord, rank, world, seed, **defaults)
    shard._cursor = StreamCursor(**{k: int(v)
                                    for k, v in cursor_state.items()})
    return shard


def make_stream_shards(ds, n: int, *, equal: bool = False,
                       seed: Optional[int] = None,
                       batch_size: Optional[int] = 256,
                       batch_format: str = "numpy",
                       drop_last: bool = False,
                       prefetch_batches=2,
                       device_index: Optional[int] = None,
                       max_in_flight: Optional[int] = None
                       ) -> List[StreamShard]:
    """N disjoint streaming shards over one shared plan execution (the
    `Dataset.streaming_split` implementation)."""
    import cloudpickle

    ops = list(getattr(ds, "_ops", None) or [])
    if not ops:
        # Materialized dataset: re-enter the lazy path so the coordinator
        # has a plan to execute (blocks ride the read-task closures).
        from ray_tpu.data.dataset import from_blocks

        ds = from_blocks(list(ds.iter_blocks()), ds._parallelism)
        ops = ds._ops
    Coordinator = ray_tpu.remote(_StreamCoordinator)
    # graftlint: allow[hot-pickle] plan ships once at stream setup, never per block
    payload = cloudpickle.dumps(ops)
    coord = Coordinator.options(num_cpus=0).remote(
        payload, ds._parallelism, seed, n, equal, max_in_flight)
    return [StreamShard(coord, r, n, seed, batch_size=batch_size,
                        batch_format=batch_format, drop_last=drop_last,
                        prefetch_batches=prefetch_batches,
                        device_index=device_index)
            for r in range(n)]


def shutdown_shards(shards: List[StreamShard]) -> None:
    """Kill the coordinator(s) behind a set of shards (stream teardown)."""
    seen = set()
    for s in shards:
        coord = getattr(s, "_coord", None)
        if coord is None or id(coord) in seen:
            continue
        seen.add(id(coord))
        try:
            ray_tpu.kill(coord)
        except Exception:
            pass


# ----------------------------------------------------- local (single-rank)

def make_local_iterator(ds, *, batch_size: Optional[int] = 256,
                        batch_format: str = "numpy", drop_last: bool = False,
                        prefetch_batches=2,
                        device_index: Optional[int] = None,
                        cursor: Optional[StreamCursor] = None
                        ) -> StreamingIterator:
    """The `Dataset.iter_batches(prefetch_batches=N)` implementation: the
    producer thread drives `ds.iter_blocks()` (bounded in-flight execution
    + incremental stats) and the consumer pops prefetched batches."""

    def source(cur: StreamCursor) -> Iterator[Tuple[int, Block]]:
        for i, block in enumerate(ds.iter_blocks()):
            if i < cur.block_offset:
                continue
            yield i, block

    return StreamingIterator(source, batch_size=batch_size,
                             batch_format=batch_format, drop_last=drop_last,
                             prefetch_batches=prefetch_batches,
                             device_index=device_index, cursor=cursor)
