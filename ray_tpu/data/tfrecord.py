"""TFRecord I/O without TensorFlow.

Reference analog: python/ray/data/read_api.py read_tfrecords /
Dataset.write_tfrecords (which delegate to TF or a pyarrow extension).
TPU-native stance: TFRecord is just a framing format + tf.train.Example
protos, both simple enough to speak directly — a TPU shop feeding JAX input
pipelines should not need a TensorFlow import for its storage format.

Wire format per record:
    uint64 LE  length
    uint32 LE  masked crc32c(length bytes)
    bytes      data
    uint32 LE  masked crc32c(data)

tf.train.Example subset (proto3 wire format, hand-coded):
    Example{ features:1 = Features{ feature:1 = map<string, Feature> } }
    Feature{ bytes_list:1 | float_list:2 | int64_list:3 }
    *List{ value:1 (repeated; numeric lists packed or unpacked) }
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List

import numpy as np

from ray_tpu.data._crc32c import crc32c


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- framing

def write_records(path: str, records: Iterator[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for data in records:
            length = struct.pack("<Q", len(data))
            f.write(length)
            f.write(struct.pack("<I", _masked_crc(length)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))
            n += 1
    return n


def read_records(path: str, *, verify: bool = True) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            (lcrc,) = struct.unpack("<I", header[8:])
            if verify and _masked_crc(header[:8]) != lcrc:
                raise ValueError(f"{path}: length crc mismatch")
            data = f.read(length)
            tail = f.read(4)
            if len(data) < length or len(tail) < 4:
                raise ValueError(f"{path}: truncated record body")
            if verify and _masked_crc(data) != struct.unpack("<I", tail)[0]:
                raise ValueError(f"{path}: data crc mismatch")
            yield data


# ------------------------------------------------- protobuf wire helpers

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _iter_fields(buf: bytes):
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val, pos = buf[pos:pos + 8], pos + 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val, pos = buf[pos:pos + ln], pos + ln
        elif wire == 5:
            val, pos = buf[pos:pos + 4], pos + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


# --------------------------------------------------- Example encode/decode

def encode_example(row: Dict) -> bytes:
    """Row dict -> serialized tf.train.Example. int -> int64_list,
    float -> float_list, bytes/str -> bytes_list; list/ndarray values
    become multi-value lists."""
    feats = bytearray()
    for key, value in row.items():
        if value is None:
            continue  # absent feature (TF semantics; ragged-row padding)
        if isinstance(value, np.ndarray):
            value = value.tolist()
        elif isinstance(value, np.generic):
            value = value.item()  # np.bool_/np.int64/np.float32 -> python
        if not isinstance(value, (list, tuple)):
            value = [value]
        value = [v.item() if isinstance(v, np.generic) else v for v in value]
        # Classify by ALL elements: [1, 2.5] must take the float_list branch
        # (int64_list would silently truncate 2.5 -> 2).
        if value and all(isinstance(v, (bool, int, np.integer))
                         for v in value):
            payload = bytearray()
            for v in value:
                payload += _varint(int(v) & 0xFFFFFFFFFFFFFFFF)
            # int64_list with packed values
            feature = _len_delim(3, _tag(1, 2) + _varint(len(payload))
                                 + bytes(payload))
        elif value and all(isinstance(v, (bool, int, float, np.integer,
                                          np.floating)) for v in value):
            payload = b"".join(struct.pack("<f", float(v)) for v in value)
            feature = _len_delim(2, _tag(1, 2) + _varint(len(payload))
                                 + payload)
        else:
            items = b""
            for v in value:
                if isinstance(v, str):
                    v = v.encode("utf-8")
                items += _len_delim(1, bytes(v))
            feature = _len_delim(1, items)
        entry = _len_delim(1, key.encode("utf-8")) + _len_delim(2, feature)
        feats += _len_delim(1, entry)
    # Example{features:1 = Features{feature:1 = repeated map entries}}:
    # `feats` is already the Features message body.
    return _len_delim(1, bytes(feats))


def _decode_list(kind: int, buf: bytes) -> List:
    values: List = []
    for field, wire, val in _iter_fields(buf):
        if field != 1:
            continue
        if kind == 1:              # bytes_list
            values.append(val)
        elif kind == 2:            # float_list
            if wire == 5:
                values.append(struct.unpack("<f", val)[0])
            else:                  # packed
                values.extend(
                    struct.unpack(f"<{len(val) // 4}f", val))
        else:                      # int64_list
            if wire == 0:
                v = val
                values.append(v - (1 << 64) if v >= (1 << 63) else v)
            else:                  # packed varints
                pos = 0
                while pos < len(val):
                    v, pos = _read_varint(val, pos)
                    values.append(v - (1 << 64) if v >= (1 << 63) else v)
    return values


def decode_example(data: bytes) -> Dict:
    """Serialized Example -> {name: list of values}.

    Always lists: the Example proto cannot distinguish a scalar from a
    1-element list, so collapsing here would make a column ragged whenever
    list lengths vary across records ([7] -> 7 but [7, 8] -> [7, 8]). The
    datasource collapses uniformly-1-length columns per file instead."""
    row: Dict = {}
    for field, _w, features in _iter_fields(data):
        if field != 1:
            continue
        for f2, _w2, feat_map in _iter_fields(features):
            if f2 != 1:
                continue
            name, feature = None, None
            for f3, _w3, v3 in _iter_fields(feat_map):
                if f3 == 1:
                    name = v3.decode("utf-8")
                elif f3 == 2:
                    feature = v3
            if name is None or feature is None:
                continue
            value: List = []
            for kind, _w4, payload in _iter_fields(feature):
                value = _decode_list(kind, payload)
            row[name] = value
    return row
