"""Blocks: the unit of distributed data.

Reference analog: python/ray/data/block.py:256 (Block = Arrow table or
pandas DataFrame; BlockAccessor). Ours standardizes on Arrow tables —
zero-copy into numpy for the TPU host feed path — with dict-of-numpy and
pandas conversion at the edges.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
Batch = Dict[str, np.ndarray]


class NdarrayType(pa.ExtensionType):
    """Arrow extension for array-valued cells of ARBITRARY shape/dtype
    (reference analog: air ArrowTensorArray). Storage = npy-serialized
    bytes per cell, so ragged shapes concat fine and dtype survives."""

    def __init__(self):
        pa.ExtensionType.__init__(self, pa.binary(), "ray_tpu.ndarray")

    def __arrow_ext_serialize__(self):
        return b""

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        return cls()


try:
    pa.register_extension_type(NdarrayType())
except pa.ArrowKeyError:
    pass  # already registered (module re-import)


def _ndarray_cells_to_arrow(cells: np.ndarray) -> pa.ExtensionArray:
    import io

    payloads = []
    for cell in cells:
        buf = io.BytesIO()
        np.save(buf, np.asarray(cell), allow_pickle=False)
        payloads.append(buf.getvalue())
    return pa.ExtensionArray.from_storage(
        NdarrayType(), pa.array(payloads, type=pa.binary()))


def _arrow_to_ndarray_cells(col) -> np.ndarray:
    import io

    storage = col.combine_chunks().storage if hasattr(col, "combine_chunks") \
        else col.storage
    out = np.empty(len(storage), dtype=object)
    for i, payload in enumerate(storage):
        out[i] = np.load(io.BytesIO(payload.as_py()), allow_pickle=False)
    return out


def block_from_batch(batch: Union[Batch, "pa.Table", Any]) -> Block:
    if isinstance(batch, pa.Table):
        return batch
    if hasattr(batch, "to_dict") and type(batch).__module__.startswith("pandas"):
        return pa.Table.from_pandas(batch, preserve_index=False)
    if isinstance(batch, dict):
        import json as json_mod

        fields, arrays = [], []
        for k, v in batch.items():
            v = np.asarray(v)
            meta = None
            if v.ndim > 1:
                # Tensor columns: fixed-shape lists; the per-cell shape
                # rides the field metadata so (n, d1, d2, ...) columns
                # round-trip SHAPED (not flattened to (n, prod)).
                arr = pa.FixedSizeListArray.from_arrays(
                    pa.array(v.reshape(-1)), int(np.prod(v.shape[1:])))
                if v.ndim > 2:
                    meta = {b"cell_shape":
                            json_mod.dumps(list(v.shape[1:])).encode()}
            elif (v.dtype == object and len(v)
                  and isinstance(v[0], np.ndarray)):
                # Array-valued cells (possibly ragged shapes).
                arr = _ndarray_cells_to_arrow(v)
            else:
                arr = pa.array(v)
            fields.append(pa.field(k, arr.type, metadata=meta))
            arrays.append(arr)
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))
    raise TypeError(f"cannot make a block from {type(batch)}")


def _rows_column_to_numpy(values: List[Any]) -> np.ndarray:
    """Column values -> numpy, tolerating ragged list cells (variable-length
    feature lists, e.g. TFRecord int64_list columns): those become
    object-dtype cells of ndarrays instead of a np.asarray ValueError."""
    try:
        return np.asarray(values)
    except ValueError:
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = np.asarray(v) if isinstance(v, (list, tuple)) else v
        return out


def block_from_rows(rows: List[Dict[str, Any]]) -> Block:
    if not rows:
        return pa.table({})
    cols = {k: [r[k] for r in rows] for k in rows[0]}
    return block_from_batch(
        {k: _rows_column_to_numpy(v) for k, v in cols.items()})


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    def to_batch(self) -> Batch:
        import json as json_mod

        out: Batch = {}
        for name in self.block.column_names:
            col = self.block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                flat = col.combine_chunks().flatten()
                width = col.type.list_size
                arr = np.asarray(flat).reshape(-1, width)
                field = self.block.schema.field(name)
                if field.metadata and b"cell_shape" in field.metadata:
                    shape = json_mod.loads(field.metadata[b"cell_shape"])
                    arr = arr.reshape((-1,) + tuple(shape))
                out[name] = arr
            elif isinstance(col.type, NdarrayType):
                out[name] = _arrow_to_ndarray_cells(col)
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pandas(self):
        return self.block.to_pandas()

    def to_rows(self) -> Iterator[Dict[str, Any]]:
        batch = self.to_batch()
        n = self.num_rows()
        for i in range(n):
            yield {k: v[i] for k, v in batch.items()}

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if b.num_rows > 0]
        if not blocks:
            return pa.table({})
        first = blocks[0].schema
        if all(b.schema.equals(first) for b in blocks[1:]):
            return pa.concat_tables(blocks)
        return pa.concat_tables(_reconcile_schemas(blocks),
                                promote_options="permissive")


def _is_list_type(t) -> bool:
    return (pa.types.is_list(t) or pa.types.is_large_list(t)
            or pa.types.is_fixed_size_list(t))


def _reconcile_schemas(blocks: List[Block]) -> List[Block]:
    """Unify blocks whose schemas disagree: a column that is scalar T in one
    block and list<T> in another (e.g. TFRecord's per-file scalar collapse
    when list lengths vary across files) promotes the scalar side to
    1-element lists; columns absent from a block fill with nulls."""
    names: List[str] = []
    for b in blocks:
        names.extend(n for n in b.schema.names if n not in names)
    target = {}
    for n in names:
        types = [b.schema.field(n).type for b in blocks
                 if n in b.schema.names]
        list_t = next((t for t in types if _is_list_type(t)), None)
        if list_t is None:
            target[n] = types[0]
        elif all(t.equals(list_t) for t in types):
            target[n] = list_t  # uniform (incl. fixed_size): leave alone
        else:
            # Mixed scalar/fixed/variable: normalize to variable list<T>.
            target[n] = pa.list_(list_t.value_type)
    out = []
    for b in blocks:
        cols = {}
        for n in names:
            if n not in b.schema.names:
                cols[n] = pa.nulls(b.num_rows, type=target[n])
                continue
            col = b[n]
            t = target[n]
            if _is_list_type(t) and not _is_list_type(col.type):
                # Rare reconciliation path: python-level wrap is fine.
                col = pa.array(
                    [None if v is None else [v] for v in col.to_pylist()],
                    type=t)
            elif not col.type.equals(t) and _is_list_type(col.type):
                col = col.cast(t)  # fixed_size_list -> list
            cols[n] = col
        out.append(pa.table(cols))
    return out
