"""Extended connector catalog: lakehouse formats, databases, media.

Reference analog: python/ray/data/read_api.py's long tail of
connectors. Two kinds here:

  * self-contained readers (Delta Lake, WAV audio, bulk parquet) that
    need only pyarrow/stdlib — implemented fully and tested offline;
  * service/driver connectors (Mongo, BigQuery, ClickHouse, Databricks,
    Lance, Hudi, Iceberg, video) that REQUIRE their client library, as
    the reference's do — each raises a precise ImportError naming the
    missing dependency when absent, and maps the client's scan API onto
    read tasks when present.
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional

import numpy as np

from ray_tpu.data.datasource import Datasource, _FileDatasource


def _require(module: str, feature: str):
    import importlib

    try:
        return importlib.import_module(module)
    except ImportError as e:
        raise ImportError(
            f"{feature} requires the '{module.split('.')[0]}' package, "
            f"which is not installed") from e


# ------------------------------------------------------------ Delta Lake

class DeltaDatasource(Datasource):
    """Delta Lake table reader (self-contained: a Delta table is parquet
    files + a JSON transaction log). Replays `_delta_log/*.json` add/
    remove actions to resolve the LIVE file set at the latest version —
    the protocol's core — without the deltalake client library.

    Reference analog: read_api.read_delta (via deltalake.DeltaTable).
    """

    def __init__(self, table_path: str, version: Optional[int] = None):
        log_dir = os.path.join(table_path, "_delta_log")
        if not os.path.isdir(log_dir):
            raise FileNotFoundError(
                f"{table_path} is not a Delta table (no _delta_log/)")
        live: dict = {}
        ckpt_version = -1
        # Checkpointed tables (writers checkpoint every ~10 commits and
        # expire older JSON): seed the live set from the parquet
        # checkpoint, then replay only newer JSON commits. Ignoring the
        # checkpoint would silently drop every file it records.
        last_ckpt = os.path.join(log_dir, "_last_checkpoint")
        if os.path.exists(last_ckpt) and (version is None
                                          or version > -1):
            with open(last_ckpt) as f:
                meta = json.load(f)
            ckpt_version = int(meta["version"])
            if version is not None and ckpt_version > version:
                raise ValueError(
                    f"time travel to version {version} is before the "
                    f"oldest checkpoint ({ckpt_version}); earlier JSON "
                    "commits have been expired")
            import pyarrow.parquet as pq

            parts = meta.get("parts")
            ckpt_files = ([os.path.join(
                log_dir, f"{ckpt_version:020d}.checkpoint."
                         f"{i + 1:010d}.{parts:010d}.parquet")
                for i in range(parts)] if parts else
                [os.path.join(log_dir,
                              f"{ckpt_version:020d}.checkpoint.parquet")])
            for cf in ckpt_files:
                tbl = pq.read_table(cf).to_pylist()
                for action in tbl:
                    add = action.get("add")
                    if add and add.get("path"):
                        live[add["path"]] = True
                    rm = action.get("remove")
                    if rm and rm.get("path"):
                        live.pop(rm["path"], None)
        commits = sorted(
            f for f in os.listdir(log_dir)
            if f.endswith(".json") and f[:-5].isdigit()
            and int(f[:-5]) > ckpt_version)
        if version is not None:
            commits = [c for c in commits if int(c[:-5]) <= version]
        for commit in commits:
            with open(os.path.join(log_dir, commit)) as f:
                for line in f:
                    if not line.strip():
                        continue
                    action = json.loads(line)
                    if "add" in action:
                        live[action["add"]["path"]] = True
                    elif "remove" in action:
                        live.pop(action["remove"]["path"], None)
        self.files = [os.path.join(table_path, p) for p in live]

    def read_tasks(self, parallelism, limit):
        def read_one(path):
            import pyarrow.parquet as pq

            return pq.read_table(path)

        return [lambda p=p: read_one(p) for p in self.files]


# ------------------------------------------------------------ audio / video

class AudioDatasource(_FileDatasource):
    """WAV natively via the stdlib; other codecs via soundfile if
    installed. Rows: {"amplitude": (channels, frames) f32, "sample_rate"}.
    Reference analog: read_api.read_audio."""

    def _read_file(self, path):
        if path.lower().endswith(".wav"):
            import wave

            with wave.open(path, "rb") as w:
                frames = w.readframes(w.getnframes())
                width = w.getsampwidth()
                if width == 3:  # 24-bit PCM: sign-extend to int32
                    raw = np.frombuffer(frames, dtype=np.uint8)
                    raw = raw.reshape(-1, 3)
                    arr32 = (raw[:, 0].astype(np.int32)
                             | (raw[:, 1].astype(np.int32) << 8)
                             | (raw[:, 2].astype(np.int32) << 16))
                    arr32 = (arr32 << 8) >> 8  # sign extension
                    arr = arr32.reshape(-1, w.getnchannels()).T
                elif width in (1, 2, 4):
                    dt = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
                    arr = np.frombuffer(frames, dtype=dt).reshape(
                        -1, w.getnchannels()).T
                else:
                    raise ValueError(
                        f"unsupported WAV sample width {width} in {path}")
                scale = float(2 ** (8 * width - 1))
                amp = (arr.astype(np.float32) - (128.0 if width == 1 else 0)
                       ) / (127.0 if width == 1 else scale)
                rate = w.getframerate()
        else:
            sf = _require("soundfile", "read_audio on non-WAV files")
            data, rate = sf.read(path, always_2d=True, dtype="float32")
            amp = data.T
        from ray_tpu.data.block import block_from_batch

        cell = np.empty(1, dtype=object)
        cell[0] = amp
        return block_from_batch({
            "amplitude": cell,
            "sample_rate": np.asarray([rate], dtype=np.int64),
            "path": np.asarray([path], dtype=object)})


class VideoDatasource(_FileDatasource):
    """Frames via OpenCV (one row per frame, like the reference's
    read_videos). Requires cv2."""

    def _read_file(self, path):
        cv2 = _require("cv2", "read_videos")
        cap = cv2.VideoCapture(path)
        frames, indices = [], []
        i = 0
        while True:
            ok, frame = cap.read()
            if not ok:
                break
            frames.append(frame[:, :, ::-1])  # BGR -> RGB
            indices.append(i)
            i += 1
        cap.release()
        return {"frame": frames, "frame_index": indices,
                "path": [path] * len(frames)}


# ------------------------------------------------------------- databases

class MongoDatasource(Datasource):
    """Reference analog: read_api.read_mongo (via pymongo)."""

    def __init__(self, uri: str, database: str, collection: str,
                 pipeline: Optional[List[dict]] = None):
        self.pymongo = _require("pymongo", "read_mongo")
        self.uri, self.db, self.coll = uri, database, collection
        self.pipeline = pipeline or []

    @staticmethod
    def _docs_to_block(docs: List[dict]):
        keys: List[str] = []
        for d in docs:  # union across docs: schemaless collections
            d.pop("_id", None)
            for k in d:
                if k not in keys:
                    keys.append(k)
        return {k: [d.get(k) for d in docs] for k in keys}

    def read_tasks(self, parallelism, limit):
        """Honors `parallelism` by splitting on `_id` ranges: N quantile
        boundary ids are sampled at plan time (sort + skip probes), then
        one find() per [lo, hi) range runs as its own task. Aggregation
        pipelines cannot be range-split and read in one task (the
        reference's MongoDatasource splits only find-style reads too —
        python/ray/data/_internal/datasource/mongo_datasource.py)."""
        uri, db, coll_name = self.uri, self.db, self.coll
        pymongo = self.pymongo

        if self.pipeline or parallelism <= 1:
            pipeline = self.pipeline

            def read_all():
                client = pymongo.MongoClient(uri)
                coll = client[db][coll_name]
                docs = list(coll.aggregate(pipeline) if pipeline
                            else coll.find())
                return self._docs_to_block(docs)

            return [read_all]

        client = pymongo.MongoClient(uri)
        coll = client[db][coll_name]
        count = coll.count_documents({})
        n = max(1, min(parallelism, count or 1))
        # Quantile boundaries: the _id at every count/n-th position.
        bounds = []
        for k in range(1, n):
            probe = list(coll.find({}, {"_id": 1}).sort("_id", 1)
                         .skip(k * count // n).limit(1))
            if probe:
                bounds.append(probe[0]["_id"])
        bounds = sorted(set(bounds))  # duplicates collapse on skewed ids

        def make_task(lo, hi):
            def read_range():
                cl = pymongo.MongoClient(uri)
                flt: dict = {}
                if lo is not None:
                    flt.setdefault("_id", {})["$gte"] = lo
                if hi is not None:
                    flt.setdefault("_id", {})["$lt"] = hi
                docs = list(cl[db][coll_name].find(flt))
                return self._docs_to_block(docs)

            return read_range

        edges = [None, *bounds, None]
        return [make_task(edges[i], edges[i + 1])
                for i in range(len(edges) - 1)]


class BigQueryDatasource(Datasource):
    """Reference analog: read_api.read_bigquery (google-cloud-bigquery)."""

    def __init__(self, project_id: str, query: str):
        self.bq = _require("google.cloud.bigquery", "read_bigquery")
        self.project_id, self.query = project_id, query

    def read_tasks(self, parallelism, limit):
        """Honors `parallelism` via the BigQuery Storage API: the query
        runs once into its destination table, a read session is opened
        with max_stream_count=parallelism, and each granted stream becomes
        one read task (the reference requests streams the same way —
        python/ray/data/_internal/datasource/bigquery_datasource.py:71).
        Without the storage client (or for parallelism 1) the whole result
        is fetched in one task."""
        project_id, query = self.project_id, self.query
        bq = self.bq
        try:
            from google.cloud import bigquery_storage  # type: ignore
        except ImportError:
            bigquery_storage = None

        if parallelism <= 1 or bigquery_storage is None:
            def read_all():
                client = bq.Client(project=project_id)
                return client.query(query).to_arrow()

            return [read_all]

        client = bq.Client(project=project_id)
        job = client.query(query)
        job.result()                     # wait for materialization
        dest = job.destination           # QueryJob attr, not RowIterator's
        session = bigquery_storage.BigQueryReadClient().create_read_session(
            parent=f"projects/{project_id}",
            read_session={
                "table": (f"projects/{dest.project}/datasets/"
                          f"{dest.dataset_id}/tables/{dest.table_id}"),
                "data_format": "ARROW",
            },
            max_stream_count=parallelism)

        def make_task(stream_name):
            def read_stream():
                import pyarrow as pa

                reader = (bigquery_storage.BigQueryReadClient()
                          .read_rows(stream_name))
                batches = [page.to_arrow() for page in reader.rows().pages]
                return pa.Table.from_batches(batches) if batches else None

            return read_stream

        tasks = [make_task(s.name) for s in session.streams]
        if not tasks:  # empty result set still yields one (empty) task
            def read_empty():
                return bq.Client(project=project_id).query(query).to_arrow()

            return [read_empty]
        return tasks


class ClickHouseDatasource(Datasource):
    """Reference analog: read_api.read_clickhouse (clickhouse-connect)."""

    def __init__(self, dsn: str, query: str):
        self.cc = _require("clickhouse_connect", "read_clickhouse")
        self.dsn, self.query = dsn, query

    def read_tasks(self, parallelism, limit):
        """Honors `parallelism` with count + LIMIT/OFFSET splits over the
        query as a subselect (the reference's ClickHouse datasource builds
        the same per-task offset windows). Rows must have a stable order
        for exact partitioning; ClickHouse only guarantees that with an
        ORDER BY in the query — matching the reference's documented
        requirement."""
        dsn, query = self.dsn, self.query
        cc = self.cc

        if parallelism <= 1:
            def read_all():
                return cc.get_client(dsn=dsn).query_arrow(query)

            return [read_all]

        client = cc.get_client(dsn=dsn)
        count = client.query(
            f"SELECT count() FROM ({query})").result_rows[0][0]
        n = max(1, min(parallelism, count or 1))

        def make_task(offset, length):
            def read_window():
                cl = cc.get_client(dsn=dsn)
                return cl.query_arrow(
                    f"SELECT * FROM ({query}) "
                    f"LIMIT {length} OFFSET {offset}")

            return read_window

        tasks = []
        for k in range(n):
            lo = k * count // n
            hi = (k + 1) * count // n
            if hi > lo:
                tasks.append(make_task(lo, hi - lo))
        return tasks or [make_task(0, 0)]


class DatabricksDatasource(Datasource):
    """Reference analog: read_api.read_databricks_tables
    (databricks-sql-connector)."""

    def __init__(self, server_hostname: str, http_path: str, token: str,
                 query: str):
        self.dbsql = _require("databricks.sql", "read_databricks_tables")
        self.args = (server_hostname, http_path, token)
        self.query = query

    def read_tasks(self, parallelism, limit):
        def read_all():
            host, path, token = self.args
            with self.dbsql.connect(server_hostname=host, http_path=path,
                                    access_token=token) as conn:
                with conn.cursor() as cur:
                    cur.execute(self.query)
                    return cur.fetchall_arrow()

        return [read_all]


# ----------------------------------------------------- lakehouse clients

class LanceDatasource(Datasource):
    """Reference analog: read_api.read_lance (lance package)."""

    def __init__(self, uri: str, columns: Optional[List[str]] = None):
        self.lance = _require("lance", "read_lance")
        self.uri, self.columns = uri, columns

    def read_tasks(self, parallelism, limit):
        def read_all():
            ds = self.lance.dataset(self.uri)
            return ds.to_table(columns=self.columns)

        return [read_all]


class IcebergDatasource(Datasource):
    """Reference analog: read_api.read_iceberg (pyiceberg catalog scan)."""

    def __init__(self, table_identifier: str, catalog_kwargs=None):
        self.pyiceberg = _require("pyiceberg.catalog", "read_iceberg")
        self.table_identifier = table_identifier
        self.catalog_kwargs = catalog_kwargs or {}

    def read_tasks(self, parallelism, limit):
        def read_all():
            catalog = self.pyiceberg.load_catalog(**self.catalog_kwargs)
            return catalog.load_table(self.table_identifier).scan() \
                .to_arrow()

        return [read_all]


class HudiDatasource(Datasource):
    """Reference analog: read_api.read_hudi (hudi package)."""

    def __init__(self, table_uri: str):
        self.hudi = _require("hudi", "read_hudi")
        self.table_uri = table_uri

    def read_tasks(self, parallelism, limit):
        def read_all():
            import pyarrow as pa

            table = self.hudi.HudiTable(self.table_uri)
            return pa.Table.from_batches(table.read_snapshot())

        return [read_all]


# --------------------------------------------------- framework converters

def dataframe_from(obj: Any, kind: str):
    """Common 'external dataframe -> pandas' hop used by from_modin /
    from_mars / from_daft / from_spark (the reference converts through
    pandas/arrow exactly the same way)."""
    if kind == "modin":
        _require("modin", "from_modin")
        return obj._to_pandas()
    if kind == "mars":
        _require("mars", "from_mars")
        return obj.to_pandas()
    if kind == "daft":
        _require("daft", "from_daft")
        return obj.to_pandas()
    if kind == "spark":
        _require("pyspark", "from_spark")
        return obj.toPandas()
    raise ValueError(kind)


def dask_partitions(ddf) -> List:
    """Materialize a dask collection's partitions through the ray_tpu
    dask scheduler (util/dask.py) — reference analog: from_dask via
    ray_dask_get."""
    _require("dask", "from_dask")
    import dask

    from ray_tpu.util.dask import ray_dask_get

    (parts,) = dask.base.optimize(ddf)
    keys = parts.__dask_keys__()
    return ray_dask_get(dict(parts.__dask_graph__()), keys)
