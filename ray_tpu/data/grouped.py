"""Grouped aggregation: hash-shuffle over the object plane, Arrow compute.

Reference analog: python/ray/data/grouped_data.py (GroupedData.aggregate,
map_groups) over the all-to-all shuffle ops
(_internal/execution/operators/ shuffle ops). Map tasks hash-partition each
block on the key into P sub-blocks (multi-return plasma objects); one reduce
task per partition concatenates its sub-blocks and runs the Arrow group_by
kernel. Aggregation math stays columnar (Arrow compute) end to end.
"""

from __future__ import annotations

import zlib
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, block_from_batch

_AGG_FNS = {"count": "count", "sum": "sum", "mean": "mean", "min": "min",
            "max": "max", "std": "stddev"}


def _partition_block(block: Block, key: str, num_partitions: int):
    """Map side: split one block into P hash partitions (one return each)."""
    if block.num_rows == 0:
        empty = block.slice(0, 0)
        return [empty] * num_partitions if num_partitions > 1 else empty
    col = block.column(key).to_numpy(zero_copy_only=False)
    # Process-stable hash per value: map tasks run in different worker
    # processes, so Python's salted hash() would route the same key to
    # different reduce partitions. crc32 is deterministic and unsigned
    # (numpy-vectorized for numeric keys).
    if col.dtype.kind in "iu":
        hashes = col.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    else:
        hashes = np.array(
            [zlib.crc32(v if isinstance(v, bytes) else str(v).encode())
             for v in col.tolist()], dtype=np.uint64)
    parts = (hashes % np.uint64(num_partitions)).astype(np.int64)
    out = []
    for p in range(num_partitions):
        idx = np.nonzero(parts == p)[0]
        out.append(block.take(pa.array(idx)))
    return out if num_partitions > 1 else out[0]


def _reduce_aggregate(key: str, aggs: List[tuple], *parts: Block) -> Block:
    merged = BlockAccessor.concat(list(parts))
    if merged.num_rows == 0:
        return merged
    gb = merged.group_by([key])
    arrow_aggs = [(col, _AGG_FNS[fn]) for col, fn in aggs]
    return gb.aggregate(arrow_aggs)


def _reduce_map_groups(key: str, fn: Callable, *parts: Block) -> Block:
    merged = BlockAccessor.concat(list(parts))
    if merged.num_rows == 0:
        return merged
    out_blocks = []
    col = merged.column(key).to_numpy(zero_copy_only=False)
    for value in np.unique(col):
        mask = pa.array(col == value)
        group = merged.filter(mask)
        result = fn(BlockAccessor(group).to_batch())
        out_blocks.append(block_from_batch(result))
    return BlockAccessor.concat(out_blocks)


class GroupedData:
    def __init__(self, dataset, key: str, num_partitions: Optional[int] = None):
        self._ds = dataset
        self._key = key
        self._num_partitions = num_partitions

    def _shuffle_reduce(self, reduce_fn, *reduce_args):
        from ray_tpu.data.dataset import MaterializedDataset

        blocks = [b for b in self._ds.iter_blocks() if b.num_rows > 0]
        if not blocks:
            return MaterializedDataset([])
        P = self._num_partitions or min(len(blocks), 8)
        part = ray_tpu.remote(_partition_block).options(num_returns=P)
        reduce = ray_tpu.remote(reduce_fn)
        # Map side: per-block partition tasks, P plasma returns each.
        part_refs = [part.remote(b, self._key, P) for b in blocks]
        if P == 1:
            part_refs = [[r] for r in part_refs]
        # Reduce side: partition p consumes the p-th return of every map.
        out_refs = [reduce.remote(self._key, *reduce_args,
                                  *[refs[p] for refs in part_refs])
                    for p in range(P)]
        out = [b for b in ray_tpu.get(out_refs) if b.num_rows > 0]
        return MaterializedDataset(out)

    def aggregate(self, *aggs: tuple):
        """aggs: (column, fn) pairs with fn in
        count/sum/mean/min/max/std. Returns a Dataset with one row per key
        and columns named '<col>_<arrowfn>'."""
        for col, fn in aggs:
            if fn not in _AGG_FNS:
                raise ValueError(f"unknown aggregation {fn!r} "
                                 f"(have {sorted(_AGG_FNS)})")
        return self._shuffle_reduce(_reduce_aggregate, list(aggs))

    def count(self):
        return self.aggregate((self._key, "count"))

    def sum(self, on: str):
        return self.aggregate((on, "sum"))

    def mean(self, on: str):
        return self.aggregate((on, "mean"))

    def min(self, on: str):
        return self.aggregate((on, "min"))

    def max(self, on: str):
        return self.aggregate((on, "max"))

    def std(self, on: str):
        return self.aggregate((on, "std"))

    def map_groups(self, fn: Callable[[Dict[str, np.ndarray]], Dict]):
        """fn(batch-of-one-group) -> batch; groups never straddle tasks."""
        return self._shuffle_reduce(_reduce_map_groups, fn)
