from ray_tpu.data.block import Block, BlockAccessor  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    DataIterator,
    Dataset,
    MaterializedDataset,
    from_blocks,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_csv,
    read_json,
    read_parquet,
)
