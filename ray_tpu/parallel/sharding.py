"""Logical-axis sharding rules: the GSPMD annotation layer.

Models name their array dimensions logically ("embed", "heads", ...); rules
map logical names to mesh axes. This is the mechanism by which one model
definition runs as DDP, FSDP, TP, or any combination — swap the rule set,
recompile, done. (The reference needs a different wrapper class per strategy:
DDP train_loop_utils.py:162, FSDP :188, TP inside vLLM. Here strategy is a
table.)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

MeshAxis = Union[str, Tuple[str, ...], None]
Rules = Dict[str, MeshAxis]

# Default rule set for transformer training (the 45%-MFU FSDP recipe):
#  - params shard their embed dim over fsdp, their width dims over tp
#  - batch shards over all data-ish axes; sequence over sp (ring attention)
TRAIN_RULES: Rules = {
    "batch": ("dp", "fsdp", "ep"),
    "seq": "sp",
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    # Batch axis of expert-dispatched activations (e, b, cap, d): the ep
    # component of "batch" moves to the expert dim, so batch keeps (dp, fsdp).
    "moe_batch": ("dp", "fsdp"),
    "layers": None,
    "conv_io": None,
}

# Inference: params replicated over the (absent) fsdp axis, TP over heads/mlp,
# batch over dp, kv-cache pages over dp.
SERVE_RULES: Rules = {
    "batch": "dp",
    "seq": None,
    "embed": None,
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    "moe_batch": None,
    "layers": None,
    "pages": "dp",
}


def spec_for(logical_axes: Sequence[Optional[str]], rules: Rules):
    """logical axis names (None = unsharded dim) -> PartitionSpec."""
    from jax.sharding import PartitionSpec

    entries = []
    for name in logical_axes:
        if name is None:
            entries.append(None)
            continue
        if name not in rules:
            raise KeyError(f"no sharding rule for logical axis {name!r}")
        entries.append(rules[name])
    return PartitionSpec(*entries)


def tree_specs(logical_tree, rules: Rules):
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    import jax

    return jax.tree.map(
        lambda axes: spec_for(axes, rules), logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x))


def shard_tree(tree, logical_tree, rules: Rules, mesh):
    """device_put a pytree with NamedShardings derived from logical axes."""
    import jax
    from jax.sharding import NamedSharding

    specs = tree_specs(logical_tree, rules)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), tree, specs)


def constrain(x, logical_axes: Sequence[Optional[str]],
              rules: Optional[Rules] = None):
    """with_sharding_constraint by logical axis names, using the ambient
    mesh/rules (parallel.mesh.use_mesh). No-op outside a mesh context, so
    model code can call it unconditionally.

    This pins activation shardings at layout-transition points (embedding
    gather output, pre-logits hidden state) where GSPMD's propagation
    otherwise picks degenerate transitions ("involuntary full
    rematerialization" — an all-replicate per step on real hardware)."""
    import jax
    from jax.sharding import NamedSharding

    from ray_tpu.parallel.mesh import current_mesh, current_rules

    mesh = current_mesh()
    rules = rules if rules is not None else current_rules()
    if mesh is None or rules is None:
        return x
    spec = spec_for(logical_axes, rules)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def named_sharding_tree(logical_tree, rules: Rules, mesh):
    import jax
    from jax.sharding import NamedSharding

    specs = tree_specs(logical_tree, rules)
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec), specs,
                        is_leaf=lambda x: not isinstance(x, (dict, list)))
