"""Sharded training step builder: DDP/FSDP/TP/SP as pjit shardings.

Reference analog: Train's prepare_model DDP/FSDP wrappers
(train/torch/train_loop_utils.py:162,188) and the per-step NCCL collectives
they imply. TPU-native: the step function is jitted once with NamedShardings
derived from logical-axis rules; XLA emits the reduce-scatter/all-gather
(FSDP) or all-reduce (DDP) over ICI and overlaps them with compute. There is
no wrapper class per strategy — the rule table IS the strategy.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel import sharding as sharding_mod
from ray_tpu.parallel.mesh import use_mesh


def init_train_state(params, optimizer) -> Dict:
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jnp.zeros((), dtype=jnp.int32),
    }


def state_logical_axes(param_axes) -> Dict:
    """Optimizer state mirrors parameter sharding (adam moments are
    param-shaped; scalars replicate)."""
    return {
        "params": param_axes,
        "opt_state": None,  # resolved structurally below
        "step": (),
    }


def _spec_like_params(opt_state, params, param_specs):
    """Give every param-shaped leaf in opt_state the matching param spec;
    everything else replicates."""
    from jax.sharding import PartitionSpec

    flat_params, _ = jax.tree.flatten(params)
    flat_specs, _ = jax.tree.flatten(
        param_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
    shape_to_spec = {}
    for p, s in zip(flat_params, flat_specs):
        shape_to_spec.setdefault((p.shape, p.dtype), s)

    def leaf_spec(leaf):
        if hasattr(leaf, "shape"):
            return shape_to_spec.get((leaf.shape, leaf.dtype), PartitionSpec())
        return PartitionSpec()

    return jax.tree.map(leaf_spec, opt_state)


def build_train_step(
    loss_fn: Callable[[Any, Any], Tuple[jax.Array, Dict]],
    optimizer: optax.GradientTransformation,
    mesh,
    param_axes,
    batch_axes,
    rules: Optional[Dict] = None,
) -> Tuple[Callable, Callable]:
    """Returns (init_fn, step_fn), both jitted with shardings.

    - loss_fn(params, batch) -> (loss, metrics)
    - param_axes / batch_axes: pytrees of logical-axis tuples
    - init_fn(params_host_or_abstract) -> sharded TrainState
    - step_fn(state, batch) -> (state, metrics); donates state
    """
    from jax.sharding import NamedSharding, PartitionSpec

    rules = rules or sharding_mod.TRAIN_RULES
    param_specs = sharding_mod.tree_specs(param_axes, rules)
    batch_specs = sharding_mod.tree_specs(batch_axes, rules)
    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    batch_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), batch_specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    repl = NamedSharding(mesh, PartitionSpec())

    def _state_shardings(state):
        opt_specs = _spec_like_params(state["opt_state"], state["params"],
                                      param_specs)
        return {
            "params": param_shardings,
            "opt_state": jax.tree.map(lambda s: NamedSharding(mesh, s), opt_specs,
                                      is_leaf=lambda x: isinstance(x, PartitionSpec)),
            "step": repl,
        }

    def init_fn(params):
        with use_mesh(mesh, rules):
            abstract = jax.eval_shape(partial(init_train_state, optimizer=optimizer),
                                      params)
            shardings = _state_shardings(abstract)
            fn = jax.jit(partial(init_train_state, optimizer=optimizer),
                         in_shardings=(param_shardings,),
                         out_shardings=shardings)
            # Host params: place them first so jit doesn't double-materialize.
            placed = jax.tree.map(
                lambda x, s: jax.device_put(x, s), params, param_shardings)
            return fn(placed), shardings

    def make_step(state_shardings):
        @partial(jax.jit,
                 in_shardings=(state_shardings, batch_shardings),
                 out_shardings=(state_shardings, repl),
                 donate_argnums=(0,))
        def step_fn(state, batch):
            with use_mesh(mesh, rules):
                (loss, metrics), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(state["params"], batch)
                updates, opt_state = optimizer.update(
                    grads, state["opt_state"], state["params"])
                params = optax.apply_updates(state["params"], updates)
                new_state = {"params": params, "opt_state": opt_state,
                             "step": state["step"] + 1}
                metrics = dict(metrics)
                metrics["grad_norm"] = optax.global_norm(grads)
                return new_state, metrics

        return step_fn

    return init_fn, make_step
