"""Pipeline parallelism: layer-partitioned stages + 1F1B microbatch schedule.

Reference analog: the reference provides PP only as a substrate — compiled
DAGs with a static per-actor schedule (python/ray/dag/compiled_dag_node.py:767,
dag_node_operation.py:17-34) plus vLLM's internal PP placement
(vllm_models.py:121-131). Here PP is first-class and deliberately NOT a mesh
axis (see parallel/mesh.py): stages are separate programs — on separate
devices in one process (LocalPipeline: the dryrun/test path and the
single-host multi-chip path) or separate actors (ActorPipeline: the
multi-host path, activations handed off through compiled-graph
DeviceChannels from a static per-actor READ/COMPUTE/WRITE schedule — no
host pickling in the steady state).

Memory model: full activation recomputation — backward re-runs the stage
forward from the saved stage INPUT (cheap to store), so live memory per
stage is bounded by the 1F1B in-flight microbatch count, independent of
model depth.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


# ------------------------------------------------------------- partitioning

def stage_layer_ranges(n_layers: int, n_stages: int) -> List[Tuple[int, int]]:
    """Split layers into contiguous per-stage ranges (balanced, remainder to
    the earlier stages which also don't carry the lm_head)."""
    base, extra = divmod(n_layers, n_stages)
    ranges, start = [], 0
    for s in range(n_stages):
        size = base + (1 if s < extra else 0)
        ranges.append((start, start + size))
        start += size
    return ranges


def split_params(params: Dict, n_stages: int) -> List[Dict]:
    """Slice a stacked-layer Llama param tree into per-stage trees. Stage 0
    holds the embedding; the last stage holds final_norm + lm_head."""
    n_layers = jax.tree.leaves(params["layers"])[0].shape[0]
    ranges = stage_layer_ranges(n_layers, n_stages)
    stages = []
    for s, (lo, hi) in enumerate(ranges):
        st: Dict[str, Any] = {
            "layers": jax.tree.map(lambda x: x[lo:hi], params["layers"])}
        if s == 0:
            st["embed"] = params["embed"]
        if s == n_stages - 1:
            st["final_norm"] = params["final_norm"]
            st["lm_head"] = params["lm_head"]
        stages.append(st)
    return stages


def merge_params(stage_params: List[Dict]) -> Dict:
    """Inverse of split_params (checkpoint save / single-device eval)."""
    layers = jax.tree.map(
        lambda *xs: jnp.concatenate(xs, axis=0),
        *[st["layers"] for st in stage_params])
    return {"embed": stage_params[0]["embed"], "layers": layers,
            "final_norm": stage_params[-1]["final_norm"],
            "lm_head": stage_params[-1]["lm_head"]}


# ------------------------------------------------------------ stage programs

def stage_apply(stage_params: Dict, x, config, *, is_first: bool,
                is_last: bool):
    """One stage's forward: tokens -> hidden (first), hidden -> hidden
    (middle), hidden -> logits (last)."""
    from ray_tpu.models import llama as llama_mod
    from ray_tpu.ops.layers import rms_norm, rope_frequencies

    cos, sin = rope_frequencies(config.head_dim, config.max_seq,
                                config.rope_theta)
    if is_first:
        x = stage_params["embed"][x].astype(config.dtype)

    layer_fn = partial(llama_mod._layer, config)
    if config.remat:
        layer_fn = jax.checkpoint(
            layer_fn, policy=jax.checkpoint_policies.nothing_saveable)

    def body(h, lp):
        return layer_fn(h, lp, cos, sin), None

    x, _ = jax.lax.scan(body, x, stage_params["layers"])
    if is_last:
        x = rms_norm(x, stage_params["final_norm"], config.norm_eps)
        x = (x @ stage_params["lm_head"].astype(config.dtype)).astype(
            jnp.float32)
    return x


def last_stage_loss(stage_params: Dict, x, targets, config,
                    is_first: bool = False):
    from ray_tpu.models.llama import next_token_ce

    logits = stage_apply(stage_params, x, config, is_first=is_first,
                         is_last=True)
    return next_token_ce(logits, targets)


def build_chunk_programs(config, chunk_ids, n_virtual: int):
    """Jitted per-chunk programs shared by LocalPipeline and
    PipelineStageActor: fwd[c] (None for the last chunk — its loss+grads
    come from bwd[c]) and bwd[c] (value_and_grad of the loss for the last
    chunk; vjp of the stage forward otherwise)."""
    fwd: Dict[int, Any] = {}
    bwd: Dict[int, Any] = {}
    for c in chunk_ids:
        is_first, is_last = c == 0, c == n_virtual - 1
        if is_last:
            def loss_f(p, x, t, _first=is_first):
                return last_stage_loss(p, x, t, config, is_first=_first)

            fwd[c] = None
            bwd[c] = jax.jit(jax.value_and_grad(loss_f, argnums=(0, 1)))
        else:
            f = partial(stage_apply, config=config, is_first=is_first,
                        is_last=False)
            fwd[c] = jax.jit(f)

            def bwd_f(p, x, g, _f=f):
                out, vjp = jax.vjp(lambda pp, xx: _f(pp, xx), p, x)
                return vjp(g)

            bwd[c] = jax.jit(bwd_f)
    return fwd, bwd


# --------------------------------------------------------------- schedule

@dataclasses.dataclass(frozen=True)
class PipeOp:
    kind: str        # "fwd" | "bwd"
    stage: int
    microbatch: int


def one_f_one_b(n_stages: int, n_microbatches: int) -> List[List[PipeOp]]:
    """Per-stage 1F1B op sequences (the static schedule a compiled DAG would
    carry, dag_node_operation.py:17). Stage s runs (n_stages - s) warmup
    forwards, then alternates 1F1B, then drains backwards."""
    assert n_microbatches >= n_stages, \
        "1F1B needs at least n_stages microbatches"
    per_stage: List[List[PipeOp]] = []
    for s in range(n_stages):
        ops: List[PipeOp] = []
        warmup = n_stages - s
        f = b = 0
        for _ in range(min(warmup, n_microbatches)):
            ops.append(PipeOp("fwd", s, f))
            f += 1
        while f < n_microbatches:
            ops.append(PipeOp("bwd", s, b))
            b += 1
            ops.append(PipeOp("fwd", s, f))
            f += 1
        while b < n_microbatches:
            ops.append(PipeOp("bwd", s, b))
            b += 1
        per_stage.append(ops)
    return per_stage


def virtual_stage_schedule(n_devices: int, v: int,
                           n_microbatches: int) -> List[List[PipeOp]]:
    """Per-DEVICE op sequences for a VIRTUAL-stage pipeline: the model is
    cut into n_devices*v chunks; device d hosts chunks d, d+n_devices, ...
    (round-robin, the Megatron virtual-pipeline PLACEMENT — it balances
    per-device memory and enables finer microbatch granularity).

    The op order is depth-(n_devices*v) 1F1B restricted to each device —
    the simple baseline kept for comparison in the bubble-accounting test;
    production paths use megatron_interleaved_schedule below, which hits
    the (p-1)/(v*m) interleaved bubble bound. PipeOp.stage is the VIRTUAL
    stage (chunk) id; device = stage % n_devices. Requires
    n_microbatches >= n_devices * v."""
    n_virtual = n_devices * v
    per_device: List[List[PipeOp]] = [[] for _ in range(n_devices)]
    for op in global_order(n_virtual, n_microbatches):
        per_device[op.stage % n_devices].append(op)
    return per_device


def megatron_interleaved_schedule(n_devices: int, v: int,
                                  n_microbatches: int) -> List[List[PipeOp]]:
    """Per-DEVICE op sequences for the Megatron interleaved 1F1B schedule
    (Narayanan et al. 2021; Megatron-LM schedules.py): chunks placed as in
    virtual_stage_schedule, but the op ORDER cycles microbatch groups of
    size n_devices through the v local chunks — warmup of
    (p-d-1)*2 + (v-1)*p forwards, then fwd/bwd steady state, then drain.
    Simulation-validated properties (see tests): deadlock-free under
    blocking in-order per-device execution, complete (one fwd + one bwd
    per chunk x microbatch), and a pipeline bubble of 2*(p-1)/v ticks vs
    2*(p*v-1) for the plain virtual order. Requires m % p == 0."""
    p, total = n_devices, n_microbatches * v
    assert n_microbatches % p == 0, \
        "interleaved schedule needs n_microbatches % n_devices == 0"

    def chunk_of(op_id: int, forward: bool) -> int:
        c = (op_id % (p * v)) // p
        return c if forward else (v - 1 - c)

    def mb_of(op_id: int) -> int:
        return (op_id // (p * v)) * p + op_id % p

    out: List[List[PipeOp]] = []
    for d in range(p):
        ops: List[PipeOp] = []
        warmup = min((p - d - 1) * 2 + (v - 1) * p, total)
        f = b = 0
        for _ in range(warmup):
            ops.append(PipeOp("fwd", chunk_of(f, True) * p + d, mb_of(f)))
            f += 1
        while f < total:
            ops.append(PipeOp("fwd", chunk_of(f, True) * p + d, mb_of(f)))
            f += 1
            ops.append(PipeOp("bwd", chunk_of(b, False) * p + d, mb_of(b)))
            b += 1
        while b < total:
            ops.append(PipeOp("bwd", chunk_of(b, False) * p + d, mb_of(b)))
            b += 1
        out.append(ops)
    return out


def linearize(per_queue: List[List[PipeOp]], n_virtual: int) -> List[PipeOp]:
    """Merge per-queue op sequences into one dependency-valid global order,
    preserving each queue's internal order (queues = stages or devices).
    fwd(s, m) needs fwd(s-1, m); bwd(s, m) needs fwd(s, m) and
    bwd(s+1, m). Asserts the sequences are deadlock-free."""
    cursors = [0] * len(per_queue)
    done = set()
    order: List[PipeOp] = []
    total = sum(len(ops) for ops in per_queue)
    while len(order) < total:
        progressed = False
        for q in range(len(per_queue)):
            while cursors[q] < len(per_queue[q]):
                op = per_queue[q][cursors[q]]
                if op.kind == "fwd":
                    ready = (op.stage == 0
                             or ("fwd", op.stage - 1, op.microbatch) in done)
                else:
                    ready = (("fwd", op.stage, op.microbatch) in done
                             and (op.stage == n_virtual - 1
                                  or ("bwd", op.stage + 1,
                                      op.microbatch) in done))
                if not ready:
                    break
                done.add((op.kind, op.stage, op.microbatch))
                order.append(op)
                cursors[q] += 1
                progressed = True
        assert progressed, "pipeline schedule deadlocked"
    return order


def global_order(n_stages: int, n_microbatches: int) -> List[PipeOp]:
    """A single sequential order respecting all inter-stage dependencies
    (for single-process execution): fwd(s, m) after fwd(s-1, m); bwd(s, m)
    after bwd(s+1, m) and fwd(s, m)."""
    return linearize(one_f_one_b(n_stages, n_microbatches), n_stages)


def submission_order(n_devices: int, interleave: int,
                     n_microbatches: int) -> List[PipeOp]:
    """The dependency-valid GLOBAL linearization whose per-device
    subsequence is the production schedule: plain 1F1B without
    interleaving, Megatron interleaved steady state with it. Shared by
    LocalPipeline (execution order) and ActorPipeline (submission order —
    actor queues execute in submission order, so this fixes each actor's
    real execution order)."""
    if interleave <= 1:
        return global_order(n_devices, n_microbatches)
    if n_microbatches % n_devices != 0:
        # Megatron's interleaved order needs m % p == 0; other microbatch
        # counts (legal for the plain order: only m >= p*v) fall back to
        # depth-p*v 1F1B rather than rejecting the step.
        return global_order(n_devices * interleave, n_microbatches)
    per_device = megatron_interleaved_schedule(
        n_devices, interleave, n_microbatches)
    return linearize(per_device, n_devices * interleave)


# ---------------------------------------------------------- local pipeline

class LocalPipeline:
    """Stages on distinct devices of one process (ICI p2p on real hardware;
    host transfer on CPU test meshes). Used by dryrun_multichip's pp leg."""

    def __init__(self, config, params, n_stages: int, optimizer,
                 devices: Optional[Sequence] = None, interleave: int = 1):
        """`interleave=v` enables virtual-stage partitioning: layers split
        into n_stages*v chunks, chunk c on device c % n_stages (see
        virtual_stage_schedule). train_step then needs n_microbatches >=
        n_stages * v."""
        self.config = config
        self.n_stages = n_stages
        self.n_virtual = n_stages * max(1, interleave)
        self.optimizer = optimizer
        devices = list(devices or jax.devices()[:n_stages])
        assert len(devices) >= n_stages
        self.devices = devices[:n_stages]
        # Device of each VIRTUAL stage (round-robin under interleaving).
        self.chunk_devices = [self.devices[c % n_stages]
                              for c in range(self.n_virtual)]
        stages = split_params(params, self.n_virtual)
        self.stage_params = [
            jax.device_put(st, d) for st, d in zip(stages, self.chunk_devices)]
        self.opt_states = [
            jax.device_put(optimizer.init(st), d)
            for st, d in zip(self.stage_params, self.chunk_devices)]
        fwd, bwd = build_chunk_programs(config, range(self.n_virtual),
                                        self.n_virtual)
        self._fwd = [fwd[c] for c in range(self.n_virtual)]
        self._bwd = [bwd[c] for c in range(self.n_virtual)]
        self._apply = jax.jit(
            lambda p, o, g: self._apply_impl(p, o, g))

    def _apply_impl(self, params, opt_state, grads):
        import optax

        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def train_step(self, tokens, n_microbatches: int) -> Dict[str, float]:
        """One 1F1B training step. tokens: (batch, seq+1) int32; batch must
        divide into n_microbatches."""
        B = tokens.shape[0]
        assert B % n_microbatches == 0
        assert n_microbatches >= self.n_virtual, (
            f"1F1B over {self.n_virtual} virtual stages "
            f"({self.n_stages} devices x interleave "
            f"{self.n_virtual // self.n_stages}) needs n_microbatches >= "
            f"{self.n_virtual}, got {n_microbatches}")
        mb = B // n_microbatches
        inputs = tokens[:, :-1]
        targets = tokens[:, 1:]
        saved_in: Dict[Tuple[int, int], Any] = {}
        fwd_out: Dict[Tuple[int, int], Any] = {}
        grads_in: Dict[Tuple[int, int], Any] = {}
        stage_grads: List[Any] = [None] * self.n_virtual
        losses = []
        last = self.n_virtual - 1
        interleave = self.n_virtual // self.n_stages
        for op in submission_order(self.n_stages, interleave,
                                   n_microbatches):
            s, m = op.stage, op.microbatch
            if op.kind == "fwd":
                if s == 0:
                    x = jax.device_put(inputs[m * mb:(m + 1) * mb],
                                       self.chunk_devices[0])
                else:
                    x = jax.device_put(fwd_out.pop((s - 1, m)),
                                       self.chunk_devices[s])
                saved_in[(s, m)] = x
                if s != last:
                    fwd_out[(s, m)] = self._fwd[s](self.stage_params[s], x)
            else:
                if s == last:
                    x = saved_in.pop((s, m))
                    t = jax.device_put(targets[m * mb:(m + 1) * mb],
                                       self.chunk_devices[s])
                    loss, (dp, dx) = self._bwd[s](self.stage_params[s], x, t)
                    losses.append(loss)
                else:
                    x = saved_in.pop((s, m))
                    g = jax.device_put(grads_in.pop((s, m)),
                                       self.chunk_devices[s])
                    dp, dx = self._bwd[s](self.stage_params[s], x, g)
                if s > 0:
                    grads_in[(s - 1, m)] = dx
                stage_grads[s] = dp if stage_grads[s] is None else jax.tree.map(
                    jnp.add, stage_grads[s], dp)
        # Optimizer step per stage (grads averaged over microbatches).
        scale = 1.0 / n_microbatches
        for s in range(self.n_virtual):
            g = jax.tree.map(lambda v: v * scale, stage_grads[s])
            self.stage_params[s], self.opt_states[s] = self._apply(
                self.stage_params[s], self.opt_states[s], g)
        return {"loss": float(sum(float(l) for l in losses) / len(losses))}

    def merged_params(self) -> Dict:
        return merge_params([jax.device_get(st) for st in self.stage_params])


# ---------------------------------------------------------- actor pipeline

def build_stage_plans(n_stages: int, interleave: int, n_microbatches: int):
    """Compile the static per-actor channel plans for one ActorPipeline
    configuration: the device-channel analog of CompiledDAG._build.

    Returns (plans, driver_channels). plans[d] is actor d's plan — its
    submission_order subsequence as ops wired to DeviceChannels, plus a
    trailing optimizer "apply" op (and, on the actor hosting the last
    chunk, a "loss_out" op that reports the step's mean loss), lowered to
    a static READ/COMPUTE/WRITE schedule (dag/schedule.py) that
    run_pipeline_loop replays once per train step. driver_channels holds
    the driver's ends: "in" (token microbatches -> chunk 0), "tgt"
    (targets -> last chunk), "loss" (mean step loss <- last chunk).

    Channel capacities admit a full step of in-flight traffic plus the
    next step's lead-in, so the only blocking reads are true data
    dependencies — the schedule order, not ring backpressure, is the
    overlap plan. FIFO channels need no microbatch tags: every schedule
    (plain 1F1B and Megatron interleaved) produces and consumes each
    boundary's microbatches in ascending order.
    """
    from ray_tpu.dag import schedule as dag_schedule
    from ray_tpu.dag.device_channel import DeviceChannel

    p, v, m = n_stages, max(1, interleave), n_microbatches
    n_virtual = p * v
    last = n_virtual - 1
    cap = 2 * m + 2
    in_ch = DeviceChannel(capacity=cap)
    tgt_ch = DeviceChannel(capacity=cap)
    loss_ch = DeviceChannel(capacity=4)
    act_ch = {s: DeviceChannel(capacity=cap) for s in range(n_virtual - 1)}
    grad_ch = {s: DeviceChannel(capacity=cap) for s in range(n_virtual - 1)}

    per_actor_ops: List[List[dict]] = [[] for _ in range(p)]
    for op in submission_order(p, v, m):
        s, mb_i = op.stage, op.microbatch
        entry = {"kind": op.kind, "chunk": s, "mb": mb_i, "reads": [],
                 "writes": [], "method": f"{op.kind}[c{s},m{mb_i}]"}
        if op.kind == "fwd":
            entry["reads"].append(("in", in_ch) if s == 0
                                  else ("act", act_ch[s - 1]))
            if s != last:
                entry["writes"].append(act_ch[s])
        else:
            entry["reads"].append(("tgt", tgt_ch) if s == last
                                  else ("grad", grad_ch[s]))
            if s > 0:
                entry["writes"].append(grad_ch[s - 1])
        per_actor_ops[s % p].append(entry)

    plans = []
    for d in range(p):
        ops = per_actor_ops[d]
        ops.append({"kind": "apply", "chunk": -1, "mb": -1, "reads": [],
                    "writes": [], "method": "apply_updates"})
        if last % p == d:
            ops.append({"kind": "loss_out", "chunk": -1, "mb": -1,
                        "reads": [], "writes": [loss_ch],
                        "method": "loss_out"})
        for i, o in enumerate(ops):
            o["node_id"] = i
        plan = {"ops": ops, "n_microbatches": m}
        plan["schedule"] = dag_schedule.compile_plan_schedule(plan)
        plans.append(plan)
    return plans, {"in": in_ch, "tgt": tgt_ch, "loss": loss_ch}


class PipelineStageActor:
    """Pipeline chunks hosted in an actor (multi-host PP). One actor per
    DEVICE/host; with interleaving it hosts several VIRTUAL stages
    (chunks). Two transports: the channel loop (run_pipeline_loop —
    device-resident hand-off, no host pickling of activations) and
    per-op actor RPC (forward/backward — the baseline path, activations
    riding the object plane as pickled host arrays)."""

    def __init__(self, chunk_ids, n_virtual: int, config_bytes: bytes,
                 chunk_params_bytes: bytes, opt_name: str = "adamw",
                 lr: float = 1e-3):
        import cloudpickle
        import optax

        self.config = cloudpickle.loads(config_bytes)
        self.chunk_ids = list(chunk_ids)
        self.n = n_virtual
        chunk_params = cloudpickle.loads(chunk_params_bytes)
        self.optimizer = (optax.adamw(lr) if opt_name == "adamw"
                          else optax.sgd(lr))
        self.params: Dict[int, Any] = {}
        self.opt_state: Dict[int, Any] = {}
        self._saved: Dict[Tuple[int, int], Any] = {}
        self._grads: Dict[int, Any] = {}
        for c, params in zip(self.chunk_ids, chunk_params):
            self.params[c] = params
            self.opt_state[c] = self.optimizer.init(params)
        self._fwd, self._bwd = build_chunk_programs(
            self.config, self.chunk_ids, n_virtual)

    def forward(self, chunk: int, mb: int, x):
        self._saved[(chunk, mb)] = x
        if self._fwd[chunk] is None:
            return True  # last chunk: loss + grads computed in backward_last
        return jax.device_get(self._fwd[chunk](self.params[chunk], x))

    def backward_last(self, chunk: int, mb: int, targets):
        x = self._saved.pop((chunk, mb))
        loss, (dp, dx) = self._bwd[chunk](self.params[chunk], x, targets)
        self._accumulate(chunk, dp)
        return float(loss), jax.device_get(dx)

    def backward(self, chunk: int, mb: int, grad_out):
        x = self._saved.pop((chunk, mb))
        dp, dx = self._bwd[chunk](self.params[chunk], x, grad_out)
        self._accumulate(chunk, dp)
        return jax.device_get(dx)

    def _accumulate(self, chunk: int, dp):
        cur = self._grads.get(chunk)
        self._grads[chunk] = dp if cur is None else jax.tree.map(
            jnp.add, cur, dp)

    def apply_updates(self, n_microbatches: int) -> bool:
        import optax

        for c in self.chunk_ids:
            g = jax.tree.map(lambda v: v / n_microbatches, self._grads[c])
            updates, self.opt_state[c] = self.optimizer.update(
                g, self.opt_state[c], self.params[c])
            self.params[c] = optax.apply_updates(self.params[c], updates)
        self._grads = {}
        return True

    def get_params_bytes(self) -> bytes:
        import cloudpickle

        return cloudpickle.dumps(
            [jax.device_get(self.params[c]) for c in self.chunk_ids])

    # -- channel transport --------------------------------------------------

    def _pipeline_compute(self, op: dict, inp: Dict[str, Any],
                          losses: List[float], n_microbatches: int):
        kind = op["kind"]
        if kind == "fwd":
            c, mb = op["chunk"], op["mb"]
            x = inp["in"] if "in" in inp else inp["act"]
            self._saved[(c, mb)] = x
            if self._fwd[c] is None:
                return None  # last chunk: loss + grads come from its bwd
            return self._fwd[c](self.params[c], x)
        if kind == "bwd":
            c, mb = op["chunk"], op["mb"]
            x = self._saved.pop((c, mb))
            if "tgt" in inp:
                loss, (dp, dx) = self._bwd[c](self.params[c], x, inp["tgt"])
                losses.append(float(loss))
            else:
                dp, dx = self._bwd[c](self.params[c], x, inp["grad"])
            self._accumulate(c, dp)
            return dx
        if kind == "apply":
            self.apply_updates(n_microbatches)
            return None
        if kind == "loss_out":
            # A jax scalar, not a float: the loss rides the device fast
            # path like every other steady-state value.
            return jnp.asarray(sum(losses) / max(1, len(losses)),
                               dtype=jnp.float32)
        raise ValueError(f"unknown pipeline op kind {kind!r}")

    def run_pipeline_loop(self, plan: dict) -> dict:
        """Persistent channel-driven stage loop — the ActorPipeline analog
        of dag/executor.run_loop. Replays the plan's static
        READ/COMPUTE/WRITE schedule once per train step until the driver
        closes the step-input channels, then cascades CLOSE downstream and
        returns {"steps", "steady_serialization"} — the latter is this
        process's serialization-counter delta over the post-warmup steps,
        which tests assert contains ZERO pickles."""
        from ray_tpu.core import serialization
        from ray_tpu.dag import schedule as dag_schedule
        from ray_tpu.dag.channel import ChannelClosed

        ops = plan["ops"]
        schedule = plan["schedule"]
        m = plan["n_microbatches"]
        read_chs = [ch for op in ops for _, ch in op["reads"]]
        write_chs = [ch for op in ops for ch in op["writes"]]
        steps = 0
        steady_base = None
        try:
            while True:
                losses: List[float] = []
                pending: Dict[int, Dict[str, Any]] = {}
                outputs: Dict[int, Any] = {}
                try:
                    for slot in schedule:
                        op = ops[slot.op_index]
                        if slot.type == dag_schedule.READ:
                            pending[slot.op_index] = {
                                role: ch.read() for role, ch in op["reads"]}
                        elif slot.type == dag_schedule.COMPUTE:
                            outputs[slot.op_index] = self._pipeline_compute(
                                op, pending.pop(slot.op_index, {}), losses, m)
                        else:  # WRITE
                            val = outputs.pop(slot.op_index)
                            for ch in op["writes"]:
                                ch.write(val)
                except ChannelClosed:
                    break
                steps += 1
                if steps == 1:
                    # Step 1 is warmup (jit compilation, channel opens);
                    # the zero-pickle invariant is asserted on the delta
                    # accumulated from here on.
                    steady_base = serialization.counter_snapshot()
        finally:
            # Mirror dag/executor.run_loop: tombstone our reads (unwedges
            # blocked upstream writers), CLOSE our writes (downstream
            # loops exit at their next read), then free retained buffers.
            for ch in read_chs:
                try:
                    ch.close_read()
                except BaseException:
                    pass
            for ch in write_chs:
                try:
                    ch.close_write(timeout=10)
                except BaseException:
                    pass
            for ch in read_chs:
                try:
                    ch.drain()
                except BaseException:
                    pass
        return {"steps": steps,
                "steady_serialization":
                    serialization.counter_delta(steady_base)
                    if steady_base is not None else None}


class ActorPipeline:
    """Driver-side coordinator for actor-hosted stages.

    Default transport "channel": stages run persistent loops
    (run_pipeline_loop) over their static READ/COMPUTE/WRITE schedules,
    activations and gradients hand off stage-to-stage through
    DeviceChannels (raw device bytes, zero host pickling), and the driver
    only feeds token/target microbatches and reads back the step loss.
    `interleave=v` gives each actor v round-robin chunks in the Megatron
    interleaved order (megatron_interleaved_schedule), so each loop's
    schedule realizes the small-bubble plan.

    transport="rpc" keeps the per-op actor-call path (one task per
    fwd/bwd, activations pickled over the object plane) — the baseline
    the microbenchmark compares against.
    """

    def __init__(self, config, params, n_stages: int, *, lr: float = 1e-3,
                 resources_per_stage: Optional[dict] = None,
                 interleave: int = 1, transport: str = "channel"):
        import cloudpickle

        import ray_tpu

        if transport not in ("channel", "rpc"):
            raise ValueError(f"unknown pipeline transport {transport!r}")
        self.config = config
        self.n_stages = n_stages
        self.interleave = max(1, interleave)
        self.n_virtual = n_stages * self.interleave
        self.transport = transport
        # Channel-loop state (channel transport only).
        self._loop_refs: List[Any] = []
        self._driver_ch: Optional[Dict[str, Any]] = None
        self._loop_m: Optional[int] = None
        self.stage_schedules: Dict[int, List[Any]] = {}
        self.last_loop_stats: Optional[List[dict]] = None
        chunks = split_params(params, self.n_virtual)
        Stage = ray_tpu.remote(PipelineStageActor)
        opts = resources_per_stage or {"num_cpus": 0}
        cfg_b = cloudpickle.dumps(config)
        self.actors = []
        for d in range(n_stages):
            ids = list(range(d, self.n_virtual, n_stages))
            self.actors.append(Stage.options(**opts).remote(
                ids, self.n_virtual, cfg_b,
                cloudpickle.dumps([chunks[c] for c in ids]), "adamw", lr))

    # -- channel transport --------------------------------------------------

    def _ensure_loops(self, n_microbatches: int) -> None:
        """(Re)launch the stage loops if none are running or the microbatch
        count changed (the static schedules are compiled per m)."""
        if self._loop_refs and self._loop_m == n_microbatches:
            return
        self._stop_loops()
        plans, chans = build_stage_plans(self.n_stages, self.interleave,
                                         n_microbatches)
        self.stage_schedules = {d: plans[d]["schedule"]
                                for d in range(self.n_stages)}
        self._driver_ch = chans
        self._loop_m = n_microbatches
        self._loop_refs = [self.actors[d].run_pipeline_loop.remote(plans[d])
                           for d in range(self.n_stages)]

    def _stop_loops(self) -> None:
        """Close the step-input channels; the loops finish in-flight work,
        cascade CLOSE downstream, and return their stats (retained in
        .last_loop_stats). A loop that died with an error raises it here."""
        import ray_tpu
        from ray_tpu.dag.channel import ChannelClosed

        if not self._loop_refs:
            return
        refs, self._loop_refs = self._loop_refs, []
        chs, self._driver_ch = self._driver_ch, None
        self._loop_m = None
        for k in ("in", "tgt"):
            try:
                chs[k].close_write(timeout=10)
            except BaseException:
                pass
        try:
            while True:
                chs["loss"].read(timeout=10)
        except (ChannelClosed, TimeoutError):
            pass
        try:
            chs["loss"].drain()
        except BaseException:
            pass
        self.last_loop_stats = ray_tpu.get(refs, timeout=120)

    def _raise_loop_error(self):
        """The loss channel closed mid-step: a stage loop died. Unwind the
        channels and surface the real task error."""
        import ray_tpu

        refs, self._loop_refs = self._loop_refs, []
        chs, self._driver_ch = self._driver_ch, None
        self._loop_m = None
        if chs is not None:
            try:
                chs["loss"].close_read()
            except BaseException:
                pass
            for k in ("in", "tgt"):
                try:
                    chs[k].close_write(timeout=5)
                except BaseException:
                    pass
        for ref in refs:
            try:
                ray_tpu.get(ref, timeout=30)
            except BaseException as e:  # noqa: BLE001 — surface task error
                raise e
        raise RuntimeError("pipeline stage loop exited unexpectedly")

    def shutdown(self) -> None:
        """Stop the stage loops (channel transport). Idempotent; the actors
        survive and a later train_step relaunches the loops."""
        self._stop_loops()

    def train_step(self, tokens, n_microbatches: int) -> Dict[str, float]:
        if self.transport == "rpc":
            return self._train_step_rpc(tokens, n_microbatches)
        import numpy as np

        from ray_tpu.dag.channel import ChannelClosed

        B = tokens.shape[0]
        assert B % n_microbatches == 0
        mb = B // n_microbatches
        inputs = np.asarray(tokens[:, :-1])
        targets = np.asarray(tokens[:, 1:])
        self._ensure_loops(n_microbatches)
        try:
            # jnp arrays so even the driver's feeds ride the device fast
            # path — the whole steady state is pickle-free.
            for i in range(n_microbatches):
                self._driver_ch["in"].write(
                    jnp.asarray(inputs[i * mb:(i + 1) * mb]), timeout=600)
            for i in range(n_microbatches):
                self._driver_ch["tgt"].write(
                    jnp.asarray(targets[i * mb:(i + 1) * mb]), timeout=600)
            loss = self._driver_ch["loss"].read(timeout=600)
        except ChannelClosed:
            self._raise_loop_error()
        return {"loss": float(loss)}

    # -- rpc transport (baseline) -------------------------------------------

    def _train_step_rpc(self, tokens, n_microbatches: int) -> Dict[str, float]:
        import numpy as np

        import ray_tpu

        B = tokens.shape[0]
        assert B % n_microbatches == 0
        mb = B // n_microbatches
        inputs = np.asarray(tokens[:, :-1])
        targets = np.asarray(tokens[:, 1:])
        fwd_ref: Dict[Tuple[int, int], Any] = {}
        bwd_ref: Dict[Tuple[int, int], Any] = {}
        loss_refs = []
        last = self.n_virtual - 1
        for op in self._submission_order(n_microbatches):
            s, m = op.stage, op.microbatch
            a = self.actors[s % self.n_stages]
            if op.kind == "fwd":
                x = (inputs[m * mb:(m + 1) * mb] if s == 0
                     else fwd_ref.pop((s - 1, m)))
                fwd_ref[(s, m)] = a.forward.remote(s, m, x)
            else:
                if s == last:
                    loss_ref, dx = a.backward_last.options(
                        num_returns=2).remote(
                            s, m, targets[m * mb:(m + 1) * mb])
                    loss_refs.append(loss_ref)
                    if s > 0:
                        bwd_ref[(s - 1, m)] = dx
                else:
                    dx = a.backward.remote(s, m, bwd_ref.pop((s, m)))
                    if s > 0:
                        bwd_ref[(s - 1, m)] = dx
        ray_tpu.get([a.apply_updates.remote(n_microbatches)
                     for a in self.actors], timeout=600)
        losses = ray_tpu.get(loss_refs, timeout=600)
        return {"loss": float(sum(losses) / len(losses))}

    def _submission_order(self, n_microbatches: int) -> List[PipeOp]:
        return submission_order(self.n_stages, self.interleave,
                                n_microbatches)

    def merged_params(self) -> Dict:
        import cloudpickle

        import ray_tpu

        # Channel loops occupy the actors' execution threads: stop them so
        # the get_params_bytes calls below can run.
        self._stop_loops()
        blobs = ray_tpu.get([a.get_params_bytes.remote()
                             for a in self.actors], timeout=600)
        # Each actor returns ITS chunks (ids d, d+p, ...): reassemble in
        # global chunk order before merging.
        chunks: List[Any] = [None] * self.n_virtual
        for d, blob in enumerate(blobs):
            lst = cloudpickle.loads(blob)
            for i, c in enumerate(range(d, self.n_virtual, self.n_stages)):
                chunks[c] = lst[i]
        return merge_params(chunks)
