"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

Absent from the reference entirely (SURVEY §2.4 marks SP/CP "must be built
natively"). Design: the sequence dimension is sharded over `sp`; each device
holds one query block and rotates KV blocks around the ICI ring with
`lax.ppermute`. Each arriving chunk is attended with the Pallas flash
kernel (ops/attention.py — O(seq) memory, never materializing the
(b, h, s, s) logits) and chunks merge by logsumexp. Communication overlaps
compute naturally because XLA pipelines the ppermute with the per-chunk
kernels.

Chunk masking exploits that shards are aligned, equal-length runs of the
global sequence: a KV chunk from rank src is — relative to this rank's
queries — entirely in the past (src < my: unmasked), the diagonal
(src == my: standard causal), or entirely in the future (src > my: fully
masked, contributes nothing). So the flash kernel needs no absolute
positions; a 3-way lax.switch picks the case per step.

Differentiable end-to-end: the flash kernel has a custom_vjp (its lse
output's cotangent folds into the backward delta term), the lse merge is
plain jnp, and ppermute has a transpose rule (backward re-rotates blocks
in reverse).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import NEG_INF, flash_attention, repeat_kv


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Call INSIDE shard_map with seq sharded over `axis_name`.

    q: (b, seq_local, h, d); k/v: (b, seq_local, hkv, d) — the local shard.
    Device i holds tokens [i*seq_local, (i+1)*seq_local).
    """
    b, sq, h, d = q.shape
    # K/V circulate the ring UNREPEATED (flash_attention is GQA-native via
    # _kv_row index maps): n_rep-times less ppermute traffic and HBM
    # residency per hop for GQA configs.
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)

    def chunk_attn(k_blk, v_blk, src):
        """(out, lse) for one KV chunk via the flash kernel; 3-way switch
        on the chunk's position relative to the diagonal."""

        def past(_):
            return flash_attention(q, k_blk, v_blk, causal=False,
                                   scale=scale, return_lse=True)

        def diagonal(_):
            return flash_attention(q, k_blk, v_blk, causal=True,
                                   scale=scale, return_lse=True)

        def future(_):
            # Constants must carry the same varying-mesh-axes set as the
            # flash branches or lax.switch rejects the branch types.
            from ray_tpu.ops.attention import _vma

            vma = _vma(q, k_blk, v_blk)
            z = jnp.zeros((b, sq, h, d), dtype=q.dtype)
            neg = jnp.full((b, h, sq), NEG_INF, dtype=jnp.float32)
            if vma:
                z = lax.pvary(z, tuple(vma))
                neg = lax.pvary(neg, tuple(vma))
            return z, neg

        if not causal:
            return past(None)
        case = jnp.int32(0) + (src == my) + 2 * (src > my)
        return lax.switch(case, [past, diagonal, future], None)

    def merge(out, lse, blk_out, blk_lse):
        """Numerically-stable softmax merge of two normalized partials."""
        lse_new = jnp.logaddexp(lse, blk_lse)           # (b, h, sq)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_blk = jnp.exp(blk_lse - lse_new).transpose(0, 2, 1)[..., None]
        return (out.astype(jnp.float32) * w_old
                + blk_out.astype(jnp.float32) * w_blk), lse_new

    # Step 0 attends the LOCAL chunk (src == my: the diagonal — every row
    # has at least its own token, so the carry lse starts finite and the
    # merge never sees exp(-inf - -inf)).
    out, lse = chunk_attn(k, v, my)
    out = out.astype(jnp.float32)
    k_blk, v_blk = k, v
    perm = [(p, (p + 1) % sp) for p in range(sp)]
    # Python loop: sp is static, XLA unrolls and pipelines ppermute/compute.
    for i in range(1, sp):
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (my - i) % sp
        blk_out, blk_lse = chunk_attn(k_blk, v_blk, src)
        out, lse = merge(out, lse, blk_out, blk_lse)
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str = "sp", causal: bool = True,
                      scale: Optional[float] = None,
                      attn_fn=None) -> jax.Array:
    """DeepSpeed-Ulysses-style SP: all_to_all swaps the sharded dim from
    sequence to heads, runs full-sequence attention locally on h/sp heads,
    and swaps back. Better for moderate sequence lengths; requires
    h % sp == 0. Call inside shard_map with seq sharded over `axis_name`."""
    from ray_tpu.ops.attention import mha_reference

    b, sq, h, d = q.shape
    sp = lax.axis_size(axis_name)
    assert h % sp == 0, f"heads {h} not divisible by sp {sp}"
    hkv = k.shape[2]
    if hkv % sp != 0:
        # The head-axis all_to_all needs sp to divide the kv-head count.
        # Repeat K/V only as much as that requires (the local attention
        # handles any remaining GQA grouping itself); full repeat to h is
        # the fallback when the minimal factor doesn't divide h evenly.
        r = sp // math.gcd(hkv, sp)
        if h % (hkv * r) != 0:
            r = h // hkv
        k = repeat_kv(k, r)
        v = repeat_kv(v, r)

    def to_heads(x):
        # (b, sq_local, h, d) -> (b, sq_global, h/sp, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    fn = attn_fn or (lambda a, b_, c: mha_reference(a, b_, c, causal=causal,
                                                    scale=scale))
    out = fn(qh, kh, vh)
    return to_seq(out)
