"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

Absent from the reference entirely (SURVEY §2.4 marks SP/CP "must be built
natively"). Design: the sequence dimension is sharded over `sp`; each device
holds one query block and rotates KV blocks around the ICI ring with
`lax.ppermute`, accumulating attention with an online softmax (log-sum-exp
carry). Communication overlaps compute naturally because XLA pipelines the
ppermute with the per-block attention matmuls.

Differentiable: the accumulation is plain jnp and ppermute has a transpose
rule, so the same code trains (backward re-rotates blocks in reverse).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import NEG_INF, repeat_kv


def _block_attn(q, k, v, scale, pos_q, pos_k, causal):
    """One KV block's contribution: returns (unnormalized acc, lse parts)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = pos_q[:, None] >= pos_k[None, :]
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m = s.max(axis=-1)                                  # (b, h, q)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)                                  # (b, h, q)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return acc, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Call INSIDE shard_map with seq sharded over `axis_name`.

    q: (b, seq_local, h, d); k/v: (b, seq_local, hkv, d) — the local shard.
    Device i holds tokens [i*seq_local, (i+1)*seq_local).
    """
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        k = repeat_kv(k, h // hkv)
        v = repeat_kv(v, h // hkv)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)

    pos_q = my * sq + jnp.arange(sq)

    def step(i, carry):
        k_blk, v_blk, m, l, acc = carry
        # The KV block currently held started at rank (my - i) mod sp.
        src = (my - i) % sp
        pos_k = src * sq + jnp.arange(sq)
        blk_acc, blk_m, blk_l = _block_attn(q, k_blk, v_blk, scale, pos_q,
                                            pos_k, causal)
        m_new = jnp.maximum(m, blk_m)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(blk_m - m_new)
        l_new = alpha * l + beta * blk_l
        acc_new = (acc * alpha.transpose(0, 2, 1)[..., None]
                   + blk_acc * beta.transpose(0, 2, 1)[..., None])
        # Rotate KV around the ring (device p sends to p+1).
        perm = [(p, (p + 1) % sp) for p in range(sp)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return k_next, v_next, m_new, l_new, acc_new

    m0 = jnp.full((b, h, sq), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, sq), dtype=jnp.float32)
    acc0 = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    carry = (k, v, m0, l0, acc0)
    # Python loop: sp is static, XLA unrolls and pipelines ppermute/compute.
    for i in range(sp):
        carry = step(i, carry)
    _, _, m, l, acc = carry
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str = "sp", causal: bool = True,
                      scale: Optional[float] = None,
                      attn_fn=None) -> jax.Array:
    """DeepSpeed-Ulysses-style SP: all_to_all swaps the sharded dim from
    sequence to heads, runs full-sequence attention locally on h/sp heads,
    and swaps back. Better for moderate sequence lengths; requires
    h % sp == 0. Call inside shard_map with seq sharded over `axis_name`."""
    from ray_tpu.ops.attention import mha_reference

    b, sq, h, d = q.shape
    sp = lax.axis_size(axis_name)
    assert h % sp == 0, f"heads {h} not divisible by sp {sp}"
    hkv = k.shape[2]
    if hkv != h:
        k = repeat_kv(k, h // hkv)
        v = repeat_kv(v, h // hkv)

    def to_heads(x):
        # (b, sq_local, h, d) -> (b, sq_global, h/sp, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    fn = attn_fn or (lambda a, b_, c: mha_reference(a, b_, c, causal=causal,
                                                    scale=scale))
    out = fn(qh, kh, vh)
    return to_seq(out)
