"""Device mesh construction and axis conventions.

The TPU-native parallelism substrate (SURVEY §2.4): one `jax.sharding.Mesh`
whose named axes carry every strategy the reference ships or outsources —

  axis   | strategy                          | reference analog
  -------+-----------------------------------+---------------------------------
  dp     | data parallel (pure replication)  | Train DDP (torch/config.py:153)
  fsdp   | data parallel + param sharding    | FSDP wrap (train_loop_utils.py:188)
  tp     | tensor parallel                   | vLLM Megatron TP (vllm_models.py:117)
  sp     | sequence/context parallel         | absent in reference (vLLM-internal)
  ep     | expert parallel                   | absent in reference

Pipeline parallelism is deliberately NOT a mesh axis: it is actor-to-actor
(compiled-graph style, see ray_tpu/parallel/pipeline.py), matching the
reference's substrate (compiled_dag_node.py) and the MPMD design in PAPERS.md.

Axis order is outer-to-inner by communication intensity: tp (most chatty)
innermost so it maps to the fastest ICI dimension; dp outermost so its
gradient reductions ride the slowest links. `jax.experimental.mesh_utils`
arranges physical devices so inner mesh axes land on adjacent chips.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

AXES = ("dp", "fsdp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.sp * self.ep * self.tp

    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.dp, self.fsdp, self.sp, self.ep, self.tp)

    @staticmethod
    def auto(num_devices: int, *, tp: int = 1, sp: int = 1, ep: int = 1,
             dp: Optional[int] = None) -> "MeshConfig":
        """Fill the fsdp axis with whatever tp/sp/ep/dp leave over."""
        used = tp * sp * ep * (dp or 1)
        if num_devices % used != 0:
            raise ValueError(f"{num_devices} devices not divisible by tp*sp*ep*dp={used}")
        return MeshConfig(dp=dp or 1, fsdp=num_devices // used, sp=sp, ep=ep, tp=tp)


def build_mesh(config: MeshConfig, devices: Optional[Sequence] = None):
    """Create the named Mesh. Uses mesh_utils for ICI-friendly layout when
    building over the full device set."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if config.num_devices != n:
        raise ValueError(
            f"mesh config wants {config.num_devices} devices, have {n}")
    shape = config.axis_sizes()
    try:
        from jax.experimental import mesh_utils

        if devices is jax.devices() or list(devices) == list(jax.devices()):
            dev_array = mesh_utils.create_device_mesh(shape)
        else:
            dev_array = np.array(devices).reshape(shape)
    except Exception:
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, AXES)


_CURRENT_MESH = None
_CURRENT_RULES = None


class use_mesh:
    """Context manager installing `mesh` (and optionally the active
    logical-axis `rules`) as ambient state. Model code uses it for explicit
    shard_map (ring attention) and activation sharding constraints
    (sharding.constrain)."""

    def __init__(self, mesh, rules=None):
        self.mesh = mesh
        self.rules = rules
        self._prev = None

    def __enter__(self):
        global _CURRENT_MESH, _CURRENT_RULES
        self._prev = (_CURRENT_MESH, _CURRENT_RULES)
        _CURRENT_MESH = self.mesh
        if self.rules is not None:
            _CURRENT_RULES = self.rules
        return self.mesh

    def __exit__(self, *exc):
        global _CURRENT_MESH, _CURRENT_RULES
        _CURRENT_MESH, _CURRENT_RULES = self._prev
        return False


def current_mesh():
    return _CURRENT_MESH


def current_rules():
    return _CURRENT_RULES


def single_device_mesh():
    import jax

    return build_mesh(MeshConfig(), devices=jax.devices()[:1])


def batch_axes() -> Tuple[str, ...]:
    """Mesh axes the global batch is sharded over."""
    return ("dp", "fsdp", "sp", "ep")


def data_parallel_size(mesh) -> int:
    return math.prod(mesh.shape[a] for a in batch_axes())
