"""Per-worker training session: report/context.

Reference analog: python/ray/train/_internal/session.py (:405 init, report
:672, get_checkpoint :786). The session lives inside each train-worker actor;
`report()` hands (metrics, checkpoint) back to the controller.
"""

from __future__ import annotations

import os
import queue
import tempfile
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


class TrainContext:
    def __init__(self, session: "TrainSession"):
        self._s = session

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_world_rank(self) -> int:
        return self._s.world_rank

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self._s.latest_checkpoint

    def get_trial_name(self) -> str:
        return self._s.run_name

    def get_storage_path(self) -> str:
        return self._s.storage_path


class TrainSession:
    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 node_rank: int, run_name: str, storage_path: str,
                 latest_checkpoint: Optional[Checkpoint] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_path = storage_path
        self.latest_checkpoint = latest_checkpoint
        self.results: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError("Not inside a ray_tpu.train worker")
    return _session


def get_context() -> TrainContext:
    return TrainContext(get_session())


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    """Report metrics (and optionally a checkpoint dir) to the controller."""
    s = get_session()
    ckpt_path = None
    if checkpoint is not None:
        ckpt_path = checkpoint.as_directory()
        s.latest_checkpoint = checkpoint
    s.results.put({"metrics": dict(metrics), "checkpoint_path": ckpt_path,
                   "rank": s.world_rank})


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().latest_checkpoint
