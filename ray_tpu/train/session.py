"""Per-worker training session: report/context.

Reference analog: python/ray/train/_internal/session.py (:405 init, report
:672, get_checkpoint :786). The session lives inside each train-worker actor;
`report()` hands (metrics, checkpoint) back to the controller.
"""

from __future__ import annotations

import os
import queue
import tempfile
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


class TrainContext:
    def __init__(self, session: "TrainSession"):
        self._s = session

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_world_rank(self) -> int:
        return self._s.world_rank

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self._s.latest_checkpoint

    def get_trial_name(self) -> str:
        return self._s.run_name

    def get_storage_path(self) -> str:
        return self._s.storage_path


class TrainSession:
    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 node_rank: int, run_name: str, storage_path: str,
                 latest_checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_path = storage_path
        self.latest_checkpoint = latest_checkpoint
        # name -> StreamShard (data/streaming.py) for THIS rank, installed
        # by the worker group when the trainer was given `datasets=`.
        self.dataset_shards: Dict[str, Any] = dataset_shards or {}
        self.results: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        # Step telemetry (train/telemetry.py): named phase seconds since
        # the last report(), closed into one step record per report.
        self.step_index = 0
        self._step_started = time.monotonic()
        self._step_started_wall = time.time()
        self._phase_acc: Dict[str, float] = {}
        # Background-attributed time (checkpoint persist) is booked
        # separately: it overlaps compute, so folding it into the phase
        # accumulator would corrupt the step's compute residual.
        self._bg_acc: Dict[str, float] = {}
        self._phase_lock = threading.Lock()
        self._ckpt_plane = None  # lazy: ray_tpu.checkpoint.CheckpointPlane

    def _close_step(self) -> Dict[str, Any]:
        """Close the current step: wall time since the last report split
        into the named phases accumulated by `step_phase`, with the
        unattributed residual booked as compute."""
        from ray_tpu.util import tracing

        now = time.monotonic()
        now_wall = time.time()
        total = max(0.0, now - self._step_started)
        with self._phase_lock:
            phases, self._phase_acc = self._phase_acc, {}
            bg, self._bg_acc = self._bg_acc, {}
        known = sum(phases.values())
        rec = {"step": self.step_index, "rank": self.world_rank,
               "total_s": total,
               "data_s": phases.pop("data", 0.0),
               "input_wait_s": phases.pop("input_wait", 0.0),
               "collective_s": phases.pop("collective", 0.0),
               "checkpoint_s": phases.pop("checkpoint", 0.0),
               "checkpoint_persist_s": bg.get("checkpoint_persist", 0.0),
               "compute_s": max(0.0, total - known),
               "other_s": sum(phases.values())}
        tracing.record_span("train:step", "train:step",
                            self._step_started_wall, now_wall,
                            rank=self.world_rank, step=self.step_index)
        self.step_index += 1
        self._step_started = now
        self._step_started_wall = now_wall
        return rec

    def note_background(self, name: str, seconds: float) -> None:
        """Book time spent OFF the train thread (background persister) so
        step records can attribute it without charging the step."""
        with self._phase_lock:
            self._bg_acc[name] = self._bg_acc.get(name, 0.0) + seconds

    def ensure_plane(self):
        """The per-worker CheckpointPlane, created on first async save."""
        if self._ckpt_plane is None:
            from ray_tpu.checkpoint import CheckpointPlane

            self._ckpt_plane = CheckpointPlane(source="train")
        return self._ckpt_plane

    def flush_checkpoints(self, timeout: Optional[float] = None) -> bool:
        """Wait for in-flight background checkpoint persists. Called by
        the worker teardown (drain/resize quiesce), never by the step."""
        if self._ckpt_plane is None:
            return True
        return self._ckpt_plane.flush(timeout)


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError("Not inside a ray_tpu.train worker")
    return _session


def get_context() -> TrainContext:
    return TrainContext(get_session())


@contextmanager
def step_phase(name: str):
    """Attribute the wrapped block of the current train step to a named
    phase ("data" / "collective" / "checkpoint"; other names land in the
    step record's `other_s`). Opens a `train:<name>` span so the phase
    also shows up in `scripts timeline --cluster`. No-op outside a train
    worker, so library code (e.g. `allreduce_gradients`) can wrap
    unconditionally."""
    s = _session
    if s is None:
        yield
        return
    from ray_tpu.util import tracing

    t0 = time.perf_counter()
    try:
        with tracing.span(f"train:{name}", "train:phase",
                          rank=s.world_rank, step=s.step_index):
            yield
    finally:
        dt = time.perf_counter() - t0
        with s._phase_lock:
            s._phase_acc[name] = s._phase_acc.get(name, 0.0) + dt


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None,
           state: Any = None, state_name: str = "state"):
    """Report metrics (and optionally a checkpoint) to the controller.
    Also closes the current telemetry step: wall time since the previous
    report, broken down by the phases `step_phase` accumulated.

    `checkpoint=` is the classic synchronous handoff: the caller already
    materialized a directory. `state=` is the async plane: the call
    stalls only for the device->host snapshot of this rank's shard and
    returns; serialization/commit happen in the background, and rank 0
    reports the checkpoint upstream once the manifest commits."""
    s = get_session()
    ckpt_path = None
    if checkpoint is not None:
        with step_phase("checkpoint"):
            ckpt_path = checkpoint.as_directory()
        s.latest_checkpoint = checkpoint
    if state is not None:
        _save_state_async(s, state, dict(metrics), state_name)
        _save_stream_cursors(s)
    telemetry = s._close_step()
    s.results.put({"metrics": dict(metrics), "checkpoint_path": ckpt_path,
                   "rank": s.world_rank, "telemetry": telemetry})


def _save_state_async(s: TrainSession, state: Any, metrics: Dict[str, Any],
                      name: str) -> None:
    """Kick off this rank's shard save; the step pays for the snapshot
    only (booked as the `checkpoint` phase). When the manifest commits,
    rank 0's on_done enqueues a checkpoint-only record so the controller
    registers the directory without waiting on the train thread."""
    directory = os.path.join(s.storage_path, f"{s.run_name}-ckpt",
                             f"step_{s.step_index:08d}")

    def on_done(info: Dict[str, Any]) -> None:
        s.note_background("checkpoint_persist", info["persist_ms"] / 1e3)
        if info["ok"] and info["committed"]:
            s.latest_checkpoint = Checkpoint(info["directory"])
            if s.world_rank == 0:
                s.results.put({"checkpoint_only": True,
                               "checkpoint_path": info["directory"],
                               "metrics": metrics, "rank": 0})

    with step_phase("checkpoint"):
        s.ensure_plane().save_async(
            state, directory, name=name, rank=s.world_rank,
            world=s.world_size, step=s.step_index, on_done=on_done)


def get_dataset_shard(name: str = "train"):
    """This rank's StreamShard for a dataset the trainer was given via
    `datasets={name: ds}` — a pipelined, backpressured, cursor-resumable
    iterator source (data/streaming.py). Returns None when the run has no
    such dataset, so train fns can fall back to synthetic input."""
    return get_session().dataset_shards.get(name)


def _save_stream_cursors(s: TrainSession) -> None:
    """Ride the async checkpoint plane with each shard's stream cursor so
    a restore resumes ingestion mid-epoch, bit-identically. One (world, 4)
    int64 leaf per dataset: the plane's axis-0 sharding persists exactly
    this rank's row, and reassembly on restore yields every rank's cursor
    regardless of which rank reads it back."""
    if not s.dataset_shards:
        return
    import numpy as np

    directory = os.path.join(s.storage_path, f"{s.run_name}-ckpt",
                             f"step_{s.step_index:08d}")
    cursors = {}
    for name, shard in s.dataset_shards.items():
        arr = np.zeros((s.world_size, 4), dtype=np.int64)
        arr[s.world_rank] = shard.cursor_row()
        cursors[name] = arr
    tree = {"world": np.asarray(s.world_size, dtype=np.int64),
            "cursors": cursors}
    with step_phase("checkpoint"):
        s.ensure_plane().save_async(
            tree, directory, name="datastream", rank=s.world_rank,
            world=s.world_size, step=s.step_index)


def restore_stream_cursors(s: TrainSession, directory: str) -> None:
    """Load saved stream cursors from a checkpoint directory into this
    session's shards (worker startup, after a failure or resize restart).
    Skipped wholesale when the saving world size differs from the current
    one — a resumed cursor indexes a per-rank shard sequence that only
    exists at the original world size."""
    if not s.dataset_shards:
        return
    from ray_tpu.checkpoint import has_manifest, restore_tree

    if not has_manifest(directory, "datastream"):
        return
    tree = restore_tree(directory, name="datastream")
    if int(tree.get("world", -1)) != s.world_size:
        return
    for name, shard in s.dataset_shards.items():
        arr = tree.get("cursors", {}).get(name)
        if arr is not None:
            shard.load_cursor(arr[s.world_rank])


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().latest_checkpoint


def load_state(template: Any = None, name: str = "state",
               shard: bool = True):
    """Restore the latest checkpoint's `report(state=...)` tree for THIS
    rank's CURRENT (rank, world) — the reshard-on-restore entry point a
    train fn calls at startup after an elastic resize or drain re-form.
    The saving world size is irrelevant: global leaves are reassembled
    from the manifest and re-sliced for the live topology, then
    `device_put` onto the current default device. Returns None when
    there is no manifest-format checkpoint yet (fresh run or a legacy
    directory)."""
    s = get_session()
    ckpt = s.latest_checkpoint
    if ckpt is None:
        return None
    from ray_tpu.checkpoint import has_manifest, restore_shard, restore_tree

    directory = ckpt.as_directory()
    if not has_manifest(directory, name):
        return None
    if shard and s.world_size > 1:
        return restore_shard(directory, rank=s.world_rank,
                             world=s.world_size, name=name,
                             template=template, device_put=True)
    return restore_tree(directory, name=name, template=template,
                        device_put=True)
