"""Per-worker training session: report/context.

Reference analog: python/ray/train/_internal/session.py (:405 init, report
:672, get_checkpoint :786). The session lives inside each train-worker actor;
`report()` hands (metrics, checkpoint) back to the controller.
"""

from __future__ import annotations

import os
import queue
import tempfile
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


class TrainContext:
    def __init__(self, session: "TrainSession"):
        self._s = session

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_world_rank(self) -> int:
        return self._s.world_rank

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self._s.latest_checkpoint

    def get_trial_name(self) -> str:
        return self._s.run_name

    def get_storage_path(self) -> str:
        return self._s.storage_path


class TrainSession:
    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 node_rank: int, run_name: str, storage_path: str,
                 latest_checkpoint: Optional[Checkpoint] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_path = storage_path
        self.latest_checkpoint = latest_checkpoint
        self.results: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        # Step telemetry (train/telemetry.py): named phase seconds since
        # the last report(), closed into one step record per report.
        self.step_index = 0
        self._step_started = time.monotonic()
        self._step_started_wall = time.time()
        self._phase_acc: Dict[str, float] = {}
        self._phase_lock = threading.Lock()

    def _close_step(self) -> Dict[str, Any]:
        """Close the current step: wall time since the last report split
        into the named phases accumulated by `step_phase`, with the
        unattributed residual booked as compute."""
        from ray_tpu.util import tracing

        now = time.monotonic()
        now_wall = time.time()
        total = max(0.0, now - self._step_started)
        with self._phase_lock:
            phases, self._phase_acc = self._phase_acc, {}
        known = sum(phases.values())
        rec = {"step": self.step_index, "rank": self.world_rank,
               "total_s": total,
               "data_s": phases.pop("data", 0.0),
               "collective_s": phases.pop("collective", 0.0),
               "checkpoint_s": phases.pop("checkpoint", 0.0),
               "compute_s": max(0.0, total - known),
               "other_s": sum(phases.values())}
        tracing.record_span("train:step", "train:step",
                            self._step_started_wall, now_wall,
                            rank=self.world_rank, step=self.step_index)
        self.step_index += 1
        self._step_started = now
        self._step_started_wall = now_wall
        return rec


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError("Not inside a ray_tpu.train worker")
    return _session


def get_context() -> TrainContext:
    return TrainContext(get_session())


@contextmanager
def step_phase(name: str):
    """Attribute the wrapped block of the current train step to a named
    phase ("data" / "collective" / "checkpoint"; other names land in the
    step record's `other_s`). Opens a `train:<name>` span so the phase
    also shows up in `scripts timeline --cluster`. No-op outside a train
    worker, so library code (e.g. `allreduce_gradients`) can wrap
    unconditionally."""
    s = _session
    if s is None:
        yield
        return
    from ray_tpu.util import tracing

    t0 = time.perf_counter()
    try:
        with tracing.span(f"train:{name}", "train:phase",
                          rank=s.world_rank, step=s.step_index):
            yield
    finally:
        dt = time.perf_counter() - t0
        with s._phase_lock:
            s._phase_acc[name] = s._phase_acc.get(name, 0.0) + dt


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    """Report metrics (and optionally a checkpoint dir) to the controller.
    Also closes the current telemetry step: wall time since the previous
    report, broken down by the phases `step_phase` accumulated."""
    s = get_session()
    ckpt_path = None
    if checkpoint is not None:
        with step_phase("checkpoint"):
            ckpt_path = checkpoint.as_directory()
        s.latest_checkpoint = checkpoint
    telemetry = s._close_step()
    s.results.put({"metrics": dict(metrics), "checkpoint_path": ckpt_path,
                   "rank": s.world_rank, "telemetry": telemetry})


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().latest_checkpoint
