"""Train worker group: N actors in a placement group running the user fn.

Reference analog: train/_internal/worker_group.py + v2 worker_group.py:102
(poll_status:421). Each worker is an actor; the user train function runs on
a thread inside it; `session.report` results are polled by the controller.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.placement_group import placement_group, remove_placement_group
from ray_tpu.runtime.scheduling import PlacementGroupStrategy
from ray_tpu.train import session as session_mod
from ray_tpu.train.backend import make_backend
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig


class TrainWorker:
    """Actor hosting one rank of the training job."""

    def __init__(self, rank: int, world_size: int, run_name: str,
                 storage_path: str):
        self.rank = rank
        self.world_size = world_size
        self.run_name = run_name
        self.storage_path = storage_path
        self.session: Optional[session_mod.TrainSession] = None
        self.thread: Optional[threading.Thread] = None

    def setup_backend(self, backend_name, group_name: str):
        backend = make_backend(backend_name)
        backend.on_start(self.rank, self.world_size, group_name)
        self._backend = backend
        self._group_name = group_name
        return True

    def start_training(self, train_fn_payload: bytes, config: Dict,
                       latest_checkpoint_path: Optional[str],
                       dataset_shards: Optional[Dict[str, Any]] = None) -> bool:
        import cloudpickle

        train_fn = cloudpickle.loads(train_fn_payload)
        ckpt = Checkpoint(latest_checkpoint_path) if latest_checkpoint_path else None
        self.session = session_mod.init_session(
            world_rank=self.rank, world_size=self.world_size,
            local_rank=self.rank, node_rank=0, run_name=self.run_name,
            storage_path=self.storage_path, latest_checkpoint=ckpt,
            dataset_shards=dataset_shards)
        if dataset_shards and latest_checkpoint_path:
            # Resume ingestion where the checkpoint left it (mid-epoch,
            # bit-identical visit order). Best-effort: a checkpoint from
            # before the run had streaming datasets simply has no cursors.
            try:
                session_mod.restore_stream_cursors(
                    self.session, latest_checkpoint_path)
            except Exception:
                pass

        def run():
            try:
                if config:
                    train_fn(config)
                else:
                    try:
                        train_fn({})
                    except TypeError:
                        train_fn()
            except BaseException as e:  # noqa: BLE001 - reported to controller
                self.session.error = e
                self.session.results.put(
                    {"error": traceback.format_exc(), "rank": self.rank})
            finally:
                # Land in-flight background checkpoint persists before
                # declaring the rank finished, so a commit (and rank 0's
                # checkpoint-only record) can't race the controller's
                # final poll.
                try:
                    from ray_tpu.config import cfg

                    self.session.flush_checkpoints(cfg().ckpt_flush_timeout_s)
                except Exception:
                    pass
                self.session.finished.set()

        self.thread = threading.Thread(target=run, daemon=True,
                                       name="train-driver")
        self.thread.start()
        return True

    def poll(self, max_results: int = 16) -> Dict[str, Any]:
        """Drain queued results; report liveness."""
        out: List[Dict] = []
        if self.session is not None:
            while len(out) < max_results and not self.session.results.empty():
                out.append(self.session.results.get_nowait())
        finished = self.session is not None and self.session.finished.is_set()
        error = None
        if self.session is not None and self.session.error is not None:
            error = repr(self.session.error)
        return {"results": out, "finished": finished, "error": error,
                "rank": self.rank}

    def flush_checkpoints(self, timeout: float = 30.0) -> bool:
        """Block until this rank's background checkpoint persists finish
        (drain path: called AFTER quiesce — the train step itself never
        waits for persistence)."""
        if self.session is None:
            return True
        return self.session.flush_checkpoints(timeout)

    def shutdown_backend(self):
        if getattr(self, "_backend", None) is not None:
            self._backend.on_shutdown(self.rank, self.world_size, self._group_name)
        return True


class WorkerGroup:
    def __init__(self, scaling: ScalingConfig, run_name: str, storage_path: str):
        self.scaling = scaling
        self.run_name = run_name
        self.storage_path = storage_path
        self.pg = None
        self.workers: List = []
        self.group_name: Optional[str] = None

    def start(self, backend_name, group_name: str):
        self.group_name = group_name
        res = self.scaling.worker_resources()
        bundles = [dict(res) for _ in range(self.scaling.num_workers)]
        self.pg = placement_group(bundles, strategy=self.scaling.placement_strategy,
                                  name=f"train-{self.run_name}")
        if not self.pg.wait(120):
            raise RuntimeError("placement group for train workers not ready")
        # max_task_retries lets a poll interrupted by connection loss
        # re-resolve through the GCS, where a slice-lost death surfaces as
        # a typed TpuSliceLostError (gang-failure signal) instead of a
        # generic "connection lost".
        WorkerActor = ray_tpu.remote(max_task_retries=2)(TrainWorker)
        self.workers = [
            WorkerActor.options(
                num_cpus=res.get("CPU", 0), num_tpus=res.get("TPU", 0),
                resources={k: v for k, v in res.items()
                           if k not in ("CPU", "TPU")},
                scheduling_strategy=PlacementGroupStrategy(self.pg, i)).remote(
                rank=i, world_size=self.scaling.num_workers,
                run_name=self.run_name, storage_path=self.storage_path)
            for i in range(self.scaling.num_workers)]
        # Backend setup runs concurrently (collective rendezvous needs it).
        ray_tpu.get([w.setup_backend.remote(backend_name, group_name)
                     for w in self.workers], timeout=300)

    def start_training(self, train_fn, config, latest_checkpoint_path,
                       dataset_shards: Optional[Dict[str, List]] = None):
        """`dataset_shards`: name -> per-rank StreamShard list (length
        num_workers), built by the controller via make_stream_shards."""
        import cloudpickle

        payload = cloudpickle.dumps(train_fn)
        refs = []
        for i, w in enumerate(self.workers):
            per_rank = ({name: shards[i]
                         for name, shards in dataset_shards.items()}
                        if dataset_shards else None)
            refs.append(w.start_training.remote(
                payload, config, latest_checkpoint_path, per_rank))
        ray_tpu.get(refs, timeout=300)

    def poll(self) -> List[Dict]:
        return ray_tpu.get([w.poll.remote() for w in self.workers], timeout=120)

    def abort_collectives(self, reason: str = "gang restart"):
        """Unblock any worker still inside a blocking collective op.

        Driver-side: writes the group's KV abort flag via
        `abort_collective_group`; every surviving rank's watchdog observes it
        within one `collective_watchdog_interval_s` and raises
        CollectiveAbortError out of the blocked op, so the subsequent
        `shutdown()` doesn't wait on actors wedged in 120 s socket reads.
        """
        if not self.group_name:
            return
        try:
            from ray_tpu.collective import abort_collective_group

            abort_collective_group(self.group_name, reason)
        except Exception:
            pass  # GCS may already be unreachable; kill path still works

    def quiesce(self, timeout: float = 10.0):
        """Controlled-teardown prelude (drain/resize — NOT failure): close
        each rank's collective backend so training threads blocked inside
        collectives unblock LOCALLY (close aborts without propagating, so
        no group-wide abort flag and no COLLECTIVE_ABORT event) before the
        actors are killed. Without this, killing rank A mid-allreduce
        makes rank B observe a broken link and record a real abort —
        turning a clean checkpoint-resume re-form into what looks like a
        gang failure after the fact."""
        refs = []
        for w in self.workers:
            try:
                refs.append(w.shutdown_backend.remote())
            except Exception:
                pass
        try:
            ray_tpu.get(refs, timeout=timeout)
        except Exception:
            pass  # a rank may already be dead; kill path still works

    def flush_checkpoints(self, timeout: float = 30.0) -> bool:
        """Best-effort wait for every rank's in-flight checkpoint
        persists (drain/resize teardown, after `quiesce`)."""
        refs = []
        for w in self.workers:
            try:
                refs.append(w.flush_checkpoints.remote(timeout))
            except Exception:
                pass
        try:
            return all(ray_tpu.get(refs, timeout=timeout + 10))
        except Exception:
            return False

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None
