"""TrainController: the v2-style training control loop.

Reference analog: python/ray/train/v2/_internal/execution/controller/
controller.py:91 — own poll loop (no Tune wrapping), failure policy decides
group restarts, checkpoint manager tracks top-K. SURVEY §7.5 explicitly says
to build this shape rather than the v1 Tune-wrapped design.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.core.exceptions import RayTpuError
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.result import Result

logger = logging.getLogger(__name__)


_RESIZE = "__elastic_resize__"


class TrainController:
    def __init__(self, train_fn: Callable, *, train_loop_config: Optional[Dict],
                 scaling_config: ScalingConfig, run_config: RunConfig,
                 backend: Any = "none", scaling_policy=None,
                 failure_policy=None, datasets: Optional[Dict[str, Any]] = None,
                 dataset_config: Optional[Dict[str, Any]] = None):
        from ray_tpu.train.elastic import FailurePolicy, FixedScalingPolicy

        self.train_fn = train_fn
        self.train_loop_config = train_loop_config or {}
        # name -> Dataset, streamed to workers as per-rank StreamShards
        # (session.get_dataset_shard). dataset_config holds iter_batches
        # defaults (batch_size, prefetch_batches, ...).
        self.datasets = datasets or {}
        self.dataset_config = dataset_config or {}
        self.scaling = scaling_config
        self.run_config = run_config
        self.backend = backend
        self.scaling_policy = scaling_policy or FixedScalingPolicy()
        self.failure_policy = failure_policy or FailurePolicy(
            run_config.failure_config.max_failures)
        self.run_name = run_config.name or f"train-{uuid.uuid4().hex[:8]}"
        self.storage_path = run_config.resolved_storage_path()
        ckpt_cfg = run_config.checkpoint_config
        self.ckpt_manager = CheckpointManager(
            self.storage_path, ckpt_cfg.num_to_keep,
            ckpt_cfg.checkpoint_score_attribute, ckpt_cfg.checkpoint_score_order)
        self.latest_metrics: Dict = {}
        self.metrics_history: List[Dict] = []
        from ray_tpu.train.callbacks import CallbackList

        self.callbacks = CallbackList(run_config.callbacks)

    @staticmethod
    def _available_resources() -> Dict[str, float]:
        # Schedulable capacity only: a DRAINING node still advertises its
        # resources but refuses new leases and bundles, so counting it
        # would declare capacity that placement can't actually use (and
        # a drain re-form would race its own dying node).
        try:
            from ray_tpu.state.api import list_nodes

            total: Dict[str, float] = {}
            for n in list_nodes():
                if not n["alive"] or n.get("draining"):
                    continue
                for k, v in n["available"].items():
                    total[k] = total.get(k, 0.0) + v
            return total
        except Exception:
            return {}

    def run(self, poll_interval: Optional[float] = None) -> Result:
        from ray_tpu.config import cfg

        poll_interval = poll_interval or cfg().train_poll_interval_s
        world = self.scaling_policy.initial_workers(
            self.scaling, self._available_resources())
        self.callbacks.fire("on_run_start", self.run_name, self.storage_path)
        self._final_result = None
        from ray_tpu.train.telemetry import TrainTelemetry

        self.telemetry = TrainTelemetry(run_name=self.run_name)
        self._run_started = time.monotonic()
        try:
            return self._run_attempts(poll_interval, world)
        finally:
            # Fires on EVERY exit (normal, error result, exception,
            # KeyboardInterrupt): trackers must end their runs and loggers
            # close their files.
            self.callbacks.fire("on_run_end", self._final_result)

    def _run_attempts(self, poll_interval: float, world: int) -> Result:
        import dataclasses as _dc

        from ray_tpu.train.elastic import FailureDecision, is_gang_failure
        from ray_tpu.train.worker_group import WorkerGroup

        attempt = 0
        while True:
            attempt += 1
            # Per-attempt group name: a fresh collective namespace every
            # restart, so abort flags from a lost attempt can't poison the
            # next one.
            scaling = _dc.replace(self.scaling, num_workers=world)
            group = WorkerGroup(scaling, f"{self.run_name}-a{attempt}",
                                self.storage_path)
            error = None
            shards = None
            try:
                group.start(self.backend, group_name=f"{self.run_name}-a{attempt}")
                latest = self.ckpt_manager.latest_checkpoint
                shards = self._make_dataset_shards(world)
                group.start_training(
                    self.train_fn, self.train_loop_config,
                    latest.path if latest else None, shards)
                error = self._poll_until_done(group, poll_interval, world)
            except RayTpuError as e:
                error = repr(e)
            finally:
                if is_gang_failure(error):
                    # Slice loss / collective abort: surviving ranks may be
                    # wedged inside blocking collectives — unblock them
                    # before tearing the group down.
                    group.abort_collectives(error)
                elif error == _RESIZE:
                    # Controlled re-form (elastic resize / drain notice):
                    # close backends rank-locally so no rank records a
                    # COLLECTIVE_ABORT for what is a clean restart. The
                    # train threads only ever waited for snapshots; the
                    # teardown (not the steps) absorbs the background
                    # persists, then one last poll ingests commits that
                    # landed during the drain so the re-form resumes from
                    # the newest checkpoint, not the previous one.
                    group.quiesce()
                    from ray_tpu.config import cfg as _cfg

                    group.flush_checkpoints(_cfg().ckpt_flush_timeout_s)
                    try:
                        for poll in group.poll():
                            for item in poll["results"]:
                                self._ingest_item(item)
                    except Exception:
                        pass
                group.shutdown()
                self._shutdown_dataset_shards(shards)
            if error is None:
                self._final_result = Result(
                    metrics=self.latest_metrics,
                    checkpoint=self.ckpt_manager.latest_checkpoint,
                    best_checkpoints=None, path=self.storage_path,
                    metrics_dataframe=self.metrics_history, error=None,
                    telemetry=self._finalize_telemetry(attempt))
                return self._final_result
            if error == _RESIZE:
                # Controlled elastic restart: resume from the latest
                # checkpoint at the new world size (ScalingPolicy analog).
                world = self._pending_world
                logger.info("train run %s resizing to %d workers",
                            self.run_name, world)
                # A drain-notice re-form races the replacement capacity the
                # autoscaler launched at notice time: wait for schedulable
                # (non-draining) room so the new placement group doesn't
                # fail infeasible and burn a failure-policy retry.
                self._wait_for_capacity(world)
                continue
            if self.failure_policy.decide(error) == FailureDecision.RETRY:
                decision = self.scaling_policy.on_failure(
                    self.scaling, world, self._available_resources())
                if decision.kind == "resize" and decision.num_workers >= 1:
                    world = decision.num_workers
                if is_gang_failure(error):
                    latest = self.ckpt_manager.latest_checkpoint
                    logger.warning(
                        "train run %s: gang restart after slice/collective "
                        "failure (%s); %d workers resuming from %s",
                        self.run_name, error, world,
                        latest.path if latest else "scratch")
                    self.telemetry.gang_restarts += 1
                    from ray_tpu.runtime import events as events_mod

                    events_mod.emit(
                        events_mod.TRAIN_GANG_RESTART,
                        f"train run {self.run_name!r}: gang restart after "
                        f"attempt {attempt} ({error}); {world} worker(s) "
                        f"resuming from "
                        f"{latest.path if latest else 'scratch'}",
                        severity=events_mod.WARNING, source="train",
                        labels={"run": self.run_name,
                                "attempt": str(attempt)})
                else:
                    logger.warning("train run %s failed (%s); restarting with "
                                   "%d workers", self.run_name, error, world)
                # A restart typically races recovery (replacement slice
                # joining, raylets re-registering): don't burn the retry
                # budget on instantly-infeasible placement groups.
                self._wait_for_capacity(world)
                continue
            self._final_result = Result(
                metrics=self.latest_metrics,
                checkpoint=self.ckpt_manager.latest_checkpoint,
                best_checkpoints=None, path=self.storage_path,
                metrics_dataframe=self.metrics_history, error=error,
                telemetry=self._finalize_telemetry(attempt))
            return self._final_result

    def _make_dataset_shards(self, world: int) -> Optional[Dict[str, List]]:
        """Per-attempt streaming shards: name -> list of per-rank
        StreamShards over a fresh coordinator actor. equal=True so DDP
        ranks see identical batch counts (no collective divergence); the
        shuffle seed derives from the run name, so every attempt of a run
        — including gang restarts — replays the same global visit order
        and a restored cursor lands on the same blocks."""
        if not self.datasets:
            return None
        import zlib

        from ray_tpu.data.streaming import make_stream_shards

        seed = zlib.crc32(self.run_name.encode())
        return {name: make_stream_shards(ds, world, equal=True, seed=seed,
                                         **self.dataset_config)
                for name, ds in self.datasets.items()}

    @staticmethod
    def _shutdown_dataset_shards(shards: Optional[Dict[str, List]]) -> None:
        if not shards:
            return
        from ray_tpu.data.streaming import shutdown_shards

        for per_rank in shards.values():
            try:
                shutdown_shards(per_rank)
            except Exception:
                pass

    def _finalize_telemetry(self, attempts: int):
        self.telemetry.attempts = attempts
        self.telemetry.wall_time_s = time.monotonic() - self._run_started
        return self.telemetry

    def _wait_for_capacity(self, world: int) -> None:
        """Bounded wait until the cluster can fit `world` workers again.
        Proceeds on timeout — placement then fails loudly and consumes a
        retry, which is the right signal when capacity never returns."""
        from ray_tpu.config import cfg

        per = self.scaling.worker_resources()
        if not per:
            return
        deadline = time.monotonic() + cfg().train_restart_resource_wait_s
        while time.monotonic() < deadline:
            avail = self._available_resources()
            if all(avail.get(res, 0.0) >= need * world
                   for res, need in per.items()):
                return
            time.sleep(0.5)
        logger.warning("train run %s: capacity for %d workers did not return "
                       "within %.0fs; attempting placement anyway",
                       self.run_name, world,
                       cfg().train_restart_resource_wait_s)

    def _surface_stall_events(self) -> None:
        """Surface hang-diagnosis events (TASK_STALLED/DEADLOCK_DETECTED
        from the GCS wait-graph detector) into the training run's log,
        once each — a run stuck behind a straggling collective rank shows
        up here instead of as silence. Best-effort: observability must
        never fail the control loop."""
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.runtime import events as events_mod

        seen = getattr(self, "_seen_stall_events", None)
        if seen is None:
            seen = self._seen_stall_events = set()
        try:
            core = worker_mod.global_worker()
            for etype in (events_mod.DEADLOCK_DETECTED,
                          events_mod.TASK_STALLED):
                for ev in core.io.run(core.gcs.call(
                        "list_events", event_type=etype, limit=20),
                        timeout=5):
                    key = (ev.get("type"), ev.get("time"))
                    if key in seen:
                        continue
                    seen.add(key)
                    logger.warning("train run %s: %s: %s", self.run_name,
                                   ev.get("type"), ev.get("message"))
                    self.telemetry.stall_events += 1
        except Exception:
            pass

    def _drain_hits_group(self, group) -> bool:
        """True when a NODE_DRAINING notice covers a node hosting one of
        this run's placement-group bundles.

        This is the proactive half of advance-notice preemption: instead of
        waiting for the deadline kill to surface as a CollectiveAbortError /
        TpuSliceLostError (the reactive gang-restart path), the controller
        sees the notice, tears the group down cleanly, and re-forms it from
        the latest checkpoint on replacement capacity — the scheduler
        already refuses draining nodes, so the new bundles land elsewhere.
        Best-effort: drain awareness must never fail the control loop."""
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.runtime import events as events_mod

        seen = getattr(self, "_seen_drain_events", None)
        if seen is None:
            seen = self._seen_drain_events = set()
        try:
            core = worker_mod.global_worker()
            draining = set()
            for ev in core.io.run(core.gcs.call(
                    "list_events", event_type=events_mod.NODE_DRAINING,
                    limit=20), timeout=5):
                if ev.get("node_id"):
                    draining.add(ev["node_id"])
                key = (ev.get("node_id"), ev.get("time"))
                if key not in seen:
                    seen.add(key)
                    logger.warning("train run %s: %s", self.run_name,
                                   ev.get("message"))
            if not draining or group.pg is None:
                return False
            info = group.pg.table()
            homes = {loc.hex() if isinstance(loc, bytes) else loc
                     for loc in info.get("locations", []) if loc}
            hit = sorted(h[:12] for h in homes & draining)
            if hit:
                latest = self.ckpt_manager.latest_checkpoint
                logger.warning(
                    "train run %s: draining node(s) %s host gang bundles; "
                    "proactive re-form from %s before the drain deadline",
                    self.run_name, ", ".join(hit),
                    latest.path if latest else "scratch")
                return True
        except Exception:
            pass
        return False

    def _poll_until_done(self, group, poll_interval: float,
                         world: int) -> Optional[str]:
        from ray_tpu.config import cfg

        last_elastic_check = time.monotonic()
        last_drain_check = time.monotonic()
        while True:
            polls = group.poll()
            now = time.monotonic()
            if (now - last_drain_check
                    >= cfg().train_drain_check_interval_s):
                last_drain_check = now
                if self._drain_hits_group(group):
                    # Re-form even without a checkpoint on record: the
                    # draining node dies at the deadline regardless, so a
                    # clean scratch restart on replacement capacity beats
                    # riding into the collective abort.
                    self._pending_world = world
                    return _RESIZE
            if (now - last_elastic_check
                    >= cfg().train_elastic_check_interval_s):
                last_elastic_check = now
                self._surface_stall_events()
                decision = self.scaling_policy.periodic(
                    self.scaling, world, self._available_resources())
                if (decision.kind == "resize"
                        and decision.num_workers != world
                        and self.ckpt_manager.latest_checkpoint is not None):
                    self._pending_world = decision.num_workers
                    return _RESIZE
            # Collate per-rank reports into rounds; rank-0 metrics win (the
            # reference reports rank-0 results by default).
            for poll in polls:
                for item in poll["results"]:
                    err = self._ingest_item(item)
                    if err is not None:
                        return err
            errors = [p["error"] for p in polls if p["error"]]
            if errors:
                return errors[0]
            if all(p["finished"] for p in polls):
                # Ranks flush background checkpoint persists before
                # flipping `finished`, so every record is already queued —
                # but one poll drains at most 16 per rank. Keep draining
                # until the queues are empty so async-committed
                # checkpoints registered here feed Result.checkpoint.
                while True:
                    leftovers = [item for poll in group.poll()
                                 for item in poll["results"]]
                    if not leftovers:
                        return None
                    for item in leftovers:
                        err = self._ingest_item(item)
                        if err is not None:
                            return err
            time.sleep(poll_interval)

    def _ingest_item(self, item: Dict) -> Optional[str]:
        """Fold one worker-queue record into controller state. Returns an
        error string for error records, else None. `checkpoint_only`
        records come from the background persister (async manifest
        commit) — they register the checkpoint without re-recording
        metrics/telemetry for the step that produced them."""
        if "error" in item:
            return item["error"]
        if item.get("checkpoint_only"):
            if item["rank"] == 0 and item.get("checkpoint_path"):
                metrics = item.get("metrics") or dict(self.latest_metrics)
                self.ckpt_manager.register(item["checkpoint_path"], metrics)
                self.callbacks.fire("on_checkpoint", item["checkpoint_path"],
                                    metrics)
            return None
        if item.get("telemetry"):
            self.telemetry.record_step(item["telemetry"])
        if item["rank"] == 0:
            metrics = item["metrics"]
            self.latest_metrics = metrics
            self.metrics_history.append(metrics)
            self.callbacks.fire("on_result", metrics,
                                len(self.metrics_history))
            if item.get("checkpoint_path"):
                self.ckpt_manager.register(item["checkpoint_path"], metrics)
                self.callbacks.fire("on_checkpoint", item["checkpoint_path"],
                                    metrics)
        return None
