"""Run callbacks + experiment-tracking integrations.

Reference analog: python/ray/air/integrations/{wandb,mlflow,comet}.py and
tune's LoggerCallback family — result hooks fired by the run controller,
with adapters for external trackers. Offline-first: the JSON and CSV
loggers always work; TensorBoard uses torch's bundled SummaryWriter;
wandb/mlflow adapters import lazily and raise a clear error when the
library is absent.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional


class Callback:
    """Hooks fired by TrainController (and Tune trials via
    tune_integration): override any subset."""

    def on_run_start(self, run_name: str, path: str) -> None:
        pass

    def on_result(self, metrics: Dict, iteration: int) -> None:
        pass

    def on_checkpoint(self, checkpoint_path: str, metrics: Dict) -> None:
        pass

    def on_run_end(self, result) -> None:
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]]):
        self._callbacks = list(callbacks or [])

    def fire(self, hook: str, *args) -> None:
        for cb in self._callbacks:
            try:
                getattr(cb, hook)(*args)
            except Exception:
                if hook == "on_run_start":
                    # Setup failures (missing wandb/mlflow, bad tracking
                    # URI) must fail FAST — swallowing them silently
                    # disables tracking for the whole run.
                    raise
                # Per-result/end hooks must never fail the run itself.
                import logging

                logging.getLogger(__name__).exception(
                    "callback %r failed in %s", cb, hook)


class JsonLoggerCallback(Callback):
    """result.json: one JSON line per reported result (tune's json logger)."""

    def __init__(self):
        self._f = None

    def on_run_start(self, run_name, path):
        os.makedirs(path, exist_ok=True)
        self._f = open(os.path.join(path, "result.json"), "a")

    def on_result(self, metrics, iteration):
        if self._f is None:
            return
        rec = {"iteration": iteration, "time": time.time(), **metrics}
        self._f.write(json.dumps(rec, default=repr) + "\n")
        self._f.flush()

    def on_run_end(self, result):
        if self._f is not None:
            self._f.close()
            self._f = None


class CSVLoggerCallback(Callback):
    """progress.csv with a header from the first result's keys."""

    def __init__(self):
        self._f = None
        self._writer = None
        self._keys: Optional[List[str]] = None

    def on_run_start(self, run_name, path):
        os.makedirs(path, exist_ok=True)
        target = os.path.join(path, "progress.csv")
        # Resumed run (same name/dir): reuse the existing header so appended
        # rows keep the column layout instead of a second mid-file header.
        if os.path.exists(target) and os.path.getsize(target) > 0:
            import csv

            with open(target, newline="") as f:
                self._keys = next(csv.reader(f), None)
        self._f = open(target, "a", newline="")
        self._writer = None

    def on_result(self, metrics, iteration):
        if self._f is None:
            return
        import csv

        if self._keys is None:
            self._keys = ["iteration"] + sorted(metrics)
        if self._writer is None:
            # DictWriter quotes embedded commas/newlines and makes the
            # header contract explicit: keys not in the first result are
            # dropped by policy, not by accident.
            self._writer = csv.DictWriter(self._f, fieldnames=self._keys,
                                          extrasaction="ignore")
            if self._f.tell() == 0:
                self._writer.writeheader()
        self._writer.writerow({"iteration": iteration, **metrics})
        self._f.flush()

    def on_run_end(self, result):
        if self._f is not None:
            self._f.close()
            self._f = None
            self._writer = None


class TensorBoardLoggerCallback(Callback):
    """Scalar metrics to TensorBoard event files (torch SummaryWriter)."""

    def __init__(self):
        self._writer = None

    def on_run_start(self, run_name, path):
        from torch.utils.tensorboard import SummaryWriter

        self._writer = SummaryWriter(log_dir=os.path.join(path, "tb"))

    def on_result(self, metrics, iteration):
        if self._writer is None:
            return
        for k, v in metrics.items():
            if isinstance(v, (int, float)):
                self._writer.add_scalar(k, v, iteration)
        self._writer.flush()

    def on_run_end(self, result):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class WandbLoggerCallback(Callback):
    """Weights & Biases adapter (air/integrations/wandb.py analog)."""

    def __init__(self, project: str, **init_kwargs):
        self.project = project
        self.init_kwargs = init_kwargs
        self._run = None

    def on_run_start(self, run_name, path):
        try:
            import wandb
        except ImportError as e:
            raise ImportError(
                "WandbLoggerCallback requires the `wandb` package") from e
        self._run = wandb.init(project=self.project, name=run_name,
                               dir=path, **self.init_kwargs)

    def on_result(self, metrics, iteration):
        if self._run is not None:
            self._run.log(metrics, step=iteration)

    def on_run_end(self, result):
        if self._run is not None:
            self._run.finish()
            self._run = None


class MlflowLoggerCallback(Callback):
    """MLflow adapter (air/integrations/mlflow.py analog)."""

    def __init__(self, experiment_name: str = "ray_tpu",
                 tracking_uri: Optional[str] = None):
        self.experiment_name = experiment_name
        self.tracking_uri = tracking_uri
        self._mlflow = None

    def on_run_start(self, run_name, path):
        try:
            import mlflow
        except ImportError as e:
            raise ImportError(
                "MlflowLoggerCallback requires the `mlflow` package") from e
        self._mlflow = mlflow
        if self.tracking_uri:
            mlflow.set_tracking_uri(self.tracking_uri)
        mlflow.set_experiment(self.experiment_name)
        mlflow.start_run(run_name=run_name)

    def on_result(self, metrics, iteration):
        if self._mlflow is not None:
            self._mlflow.log_metrics(
                {k: v for k, v in metrics.items()
                 if isinstance(v, (int, float))}, step=iteration)

    def on_run_end(self, result):
        if self._mlflow is not None:
            self._mlflow.end_run()
            self._mlflow = None
