"""Distributed gradient-boosted decision trees (the xgboost-on-ray analog).

Reference analog: python/ray/train/xgboost/ + the xgboost_ray package —
data-parallel GBDT where each worker holds a data shard and boosting
synchronizes per-split histograms (xgboost's rabit allreduce). The
reference outsources the algorithm to the xgboost C++ library; this
module implements the same training scheme natively so the capability
exists without the dependency:

  * quantile binning (uint8 bins, 256 max) computed once from a global
    sample — xgboost's "hist" tree method;
  * shard workers are actors; each boosting round ships ONE histogram
    reduction per tree level (sum of per-worker (nodes, features, bins)
    grad/hess tensors), not per-row traffic;
  * level-wise growth to max_depth with the standard regularized gain
    G_L^2/(H_L+lambda) + G_R^2/(H_R+lambda) - G^2/(H+lambda);
  * squared-error regression and binary logistic objectives.

The fitted model is plain data (arrays per tree) and predicts anywhere —
drivers, serve deployments — without the training cluster.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_MAX_BINS = 256


# ------------------------------------------------------------------ model

@dataclass
class _Tree:
    """Flat tree: node i splits on feature[i] at threshold[i]; children
    are left[i]/right[i]; leaves have feature[i] == -1 and value[i]."""
    feature: np.ndarray    # (n_nodes,) int32, -1 = leaf
    threshold: np.ndarray  # (n_nodes,) float64 (raw-space bin edge)
    left: np.ndarray       # (n_nodes,) int32
    right: np.ndarray      # (n_nodes,) int32
    value: np.ndarray      # (n_nodes,) float64 leaf weight

    def predict(self, X: np.ndarray) -> np.ndarray:
        node = np.zeros(len(X), dtype=np.int32)
        out = np.zeros(len(X), dtype=np.float64)
        live = np.arange(len(X))
        while len(live):
            f = self.feature[node[live]]
            at_leaf = f < 0
            leaf_rows = live[at_leaf]
            out[leaf_rows] = self.value[node[leaf_rows]]
            live = live[~at_leaf]
            if not len(live):
                break
            f = self.feature[node[live]]
            go_left = X[live, f] <= self.threshold[node[live]]
            node[live] = np.where(go_left, self.left[node[live]],
                                  self.right[node[live]])
        return out


@dataclass
class GBDTModel:
    trees: List[_Tree]
    base_score: float
    objective: str
    learning_rate: float

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float64)
        out = np.full(len(X), self.base_score, dtype=np.float64)
        for t in self.trees:
            out += t.predict(X)
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        raw = self.predict_raw(X)
        if self.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-raw))
        return raw


# ------------------------------------------------------------------ worker

class _ShardWorker:
    """Actor holding one data shard; all per-row work happens here."""

    def __init__(self, X: np.ndarray, y: np.ndarray, objective: str):
        self.X = np.asarray(X, dtype=np.float64)
        self.y = np.asarray(y, dtype=np.float64)
        self.objective = objective
        self.pred: Optional[np.ndarray] = None
        self.Xb: Optional[np.ndarray] = None
        self.node: Optional[np.ndarray] = None
        self.grad = self.hess = None

    def sample(self, n: int) -> np.ndarray:
        idx = np.random.default_rng(0).permutation(len(self.X))[:n]
        return self.X[idx]

    def label_sum(self) -> Tuple[float, int]:
        return float(self.y.sum()), len(self.y)

    def bin_data(self, edges: List[np.ndarray]) -> None:
        cols = [np.searchsorted(edges[f], self.X[:, f], side="left")
                for f in range(self.X.shape[1])]
        self.Xb = np.stack(cols, axis=1).astype(np.uint16)

    def set_base(self, base: float) -> None:
        self.pred = np.full(len(self.y), base, dtype=np.float64)

    def new_round(self) -> None:
        if self.objective == "binary:logistic":
            p = 1.0 / (1.0 + np.exp(-self.pred))
            self.grad = p - self.y
            self.hess = p * (1.0 - p)
        else:  # reg:squarederror
            self.grad = self.pred - self.y
            self.hess = np.ones_like(self.y)
        self.node = np.zeros(len(self.y), dtype=np.int32)

    def histograms(self, active: List[int], n_bins: int) -> np.ndarray:
        """(len(active), F, n_bins, 2) grad/hess sums — the payload of the
        per-level 'allreduce' (driver sums these across workers)."""
        F = self.Xb.shape[1]
        node_pos = {n: i for i, n in enumerate(active)}
        mask = np.isin(self.node, active)
        rows = np.nonzero(mask)[0]
        out = np.zeros((len(active), F, n_bins, 2), dtype=np.float64)
        if not len(rows):
            return out
        ni = np.vectorize(node_pos.get, otypes=[np.int64])(self.node[rows])
        for f in range(F):
            flat = ni * n_bins + self.Xb[rows, f]
            gh = np.zeros(len(active) * n_bins)
            hh = np.zeros(len(active) * n_bins)
            np.add.at(gh, flat, self.grad[rows])
            np.add.at(hh, flat, self.hess[rows])
            out[:, f, :, 0] = gh.reshape(len(active), n_bins)
            out[:, f, :, 1] = hh.reshape(len(active), n_bins)
        return out

    def apply_splits(self, splits: dict) -> None:
        """splits: node -> (feature, bin_threshold, left_id, right_id)."""
        for n, (f, bthr, lid, rid) in splits.items():
            rows = np.nonzero(self.node == n)[0]
            go_left = self.Xb[rows, f] <= bthr
            self.node[rows] = np.where(go_left, lid, rid)

    def apply_leaves(self, leaf_values: dict) -> None:
        for n, w in leaf_values.items():
            self.pred[self.node == n] += w

    def metric(self) -> Tuple[float, int]:
        if self.objective == "binary:logistic":
            p = np.clip(1.0 / (1.0 + np.exp(-self.pred)), 1e-9, 1 - 1e-9)
            loss = -(self.y * np.log(p) + (1 - self.y) * np.log(1 - p))
            return float(loss.sum()), len(self.y)
        return float(((self.pred - self.y) ** 2).sum()), len(self.y)


# ------------------------------------------------------------------ driver

@dataclass
class GBDTConfig:
    objective: str = "reg:squarederror"    # or "binary:logistic"
    num_boost_round: int = 50
    max_depth: int = 4
    learning_rate: float = 0.3
    reg_lambda: float = 1.0
    min_child_weight: float = 1.0
    max_bins: int = _MAX_BINS
    history: List[float] = field(default_factory=list)


def train(config: GBDTConfig, X: np.ndarray, y: np.ndarray,
          num_workers: int = 2) -> GBDTModel:
    """Fit a GBDT over `num_workers` shard actors.

    Network traffic per tree level is ONE (nodes, features, bins, 2)
    histogram per worker — independent of row count, the property that
    makes xgboost's distributed hist method scale."""
    import ray_tpu

    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    Worker = ray_tpu.remote(_ShardWorker)
    shards = np.array_split(np.arange(len(X)), num_workers)
    workers = [Worker.remote(X[s], y[s], config.objective) for s in shards]

    # global quantile bin edges from a per-worker sample
    samples = np.concatenate(
        ray_tpu.get([w.sample.remote(10_000 // num_workers + 1)
                     for w in workers]))
    edges = []
    for f in range(X.shape[1]):
        qs = np.quantile(samples[:, f],
                         np.linspace(0, 1, config.max_bins)[1:-1])
        edges.append(np.unique(qs))
    n_bins = max(config.max_bins, 2)
    ray_tpu.get([w.bin_data.remote(edges) for w in workers])

    # base score
    sums = ray_tpu.get([w.label_sum.remote() for w in workers])
    mean = sum(s for s, _ in sums) / max(sum(n for _, n in sums), 1)
    if config.objective == "binary:logistic":
        mean = min(max(mean, 1e-6), 1 - 1e-6)
        base = float(np.log(mean / (1 - mean)))
    else:
        base = float(mean)
    ray_tpu.get([w.set_base.remote(base) for w in workers])

    lam, trees = config.reg_lambda, []
    for _round in range(config.num_boost_round):
        ray_tpu.get([w.new_round.remote() for w in workers])
        # grow one tree, level by level
        node_stats = {}             # node id -> (G, H) once known
        feature = {0: -1}
        threshold, left, right = {}, {}, {}
        next_id = 1
        active = [0]
        for _depth in range(config.max_depth):
            if not active:
                break
            hists = ray_tpu.get(
                [w.histograms.remote(active, n_bins) for w in workers])
            H = np.sum(hists, axis=0)   # the allreduce
            splits = {}
            new_active = []
            for i, n in enumerate(active):
                g_total = H[i, :, :, 0].sum(axis=1)[0]
                h_total = H[i, :, :, 1].sum(axis=1)[0]
                node_stats[n] = (g_total, h_total)
                parent_score = g_total ** 2 / (h_total + lam)
                # best split across features/bins via cumulative sums
                gl = np.cumsum(H[i, :, :, 0], axis=1)
                hl = np.cumsum(H[i, :, :, 1], axis=1)
                gr = g_total - gl
                hr = h_total - hl
                valid = (hl >= config.min_child_weight) & \
                        (hr >= config.min_child_weight)
                gain = np.where(
                    valid,
                    gl ** 2 / (hl + lam) + gr ** 2 / (hr + lam)
                    - parent_score, -np.inf)
                f, b = np.unravel_index(np.argmax(gain), gain.shape)
                if not np.isfinite(gain[f, b]) or gain[f, b] <= 1e-12:
                    continue
                lid, rid = next_id, next_id + 1
                next_id += 2
                feature[n] = int(f)
                # raw-space threshold so the model predicts on raw data
                ed = edges[f]
                threshold[n] = float(ed[min(b, len(ed) - 1)]) \
                    if len(ed) else 0.0
                left[n], right[n] = lid, rid
                feature[lid] = feature[rid] = -1
                splits[n] = (int(f), int(b), lid, rid)
                new_active += [lid, rid]
            if not splits:
                break
            ray_tpu.get([w.apply_splits.remote(splits) for w in workers])
            # children stats appear next level; leaves settled below
            active = new_active
        # leaf weights: need (G, H) for every current leaf — one more
        # histogram pass over the final active set covers new leaves.
        leaves = [n for n in feature if feature[n] == -1]
        pending = [n for n in leaves if n not in node_stats]
        if pending:
            hists = ray_tpu.get(
                [w.histograms.remote(pending, n_bins) for w in workers])
            Hh = np.sum(hists, axis=0)
            for i, n in enumerate(pending):
                node_stats[n] = (Hh[i, :, :, 0].sum(axis=1)[0],
                                 Hh[i, :, :, 1].sum(axis=1)[0])
        leaf_values = {}
        for n in leaves:
            G, Hn = node_stats.get(n, (0.0, 0.0))
            leaf_values[n] = float(-config.learning_rate * G / (Hn + lam))
        ray_tpu.get([w.apply_leaves.remote(leaf_values) for w in workers])

        n_nodes = next_id
        tree = _Tree(
            feature=np.full(n_nodes, -1, dtype=np.int32),
            threshold=np.zeros(n_nodes), left=np.zeros(n_nodes, np.int32),
            right=np.zeros(n_nodes, np.int32), value=np.zeros(n_nodes))
        for n in range(n_nodes):
            if feature.get(n, -1) >= 0:
                tree.feature[n] = feature[n]
                tree.threshold[n] = threshold[n]
                tree.left[n] = left[n]
                tree.right[n] = right[n]
            else:
                tree.value[n] = leaf_values.get(n, 0.0)
        trees.append(tree)

        totals = ray_tpu.get([w.metric.remote() for w in workers])
        loss = sum(s for s, _ in totals) / max(sum(c for _, c in totals), 1)
        config.history.append(loss)

    return GBDTModel(trees=trees, base_score=base,
                     objective=config.objective,
                     learning_rate=config.learning_rate)
