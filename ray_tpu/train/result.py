"""Training result.

Reference analog: python/ray/air/result.py Result.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    best_checkpoints: Optional[List]
    path: str
    metrics_dataframe: Optional[List[Dict]] = None
    error: Optional[str] = None
    # Per-run step breakdown / goodput / straggler attribution
    # (train/telemetry.py TrainTelemetry); populated by TrainController.
    telemetry: Optional[Any] = None
