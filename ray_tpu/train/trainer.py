"""Public trainers.

Reference analog: python/ray/train/data_parallel_trainer.py
(DataParallelTrainer) + torch/torch_trainer.py; ours is JAX-first:

    def train_fn(config):
        ctx = ray_tpu.train.get_context()
        ... build mesh over jax.devices(), pjit step, session.report(...)

    trainer = JaxTrainer(train_fn, scaling_config=ScalingConfig(num_workers=8,
                          use_tpu=True), run_config=RunConfig(...))
    result = trainer.fit()
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainController
from ray_tpu.train.result import Result


class DataParallelTrainer:
    backend: Any = "none"

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 backend: Optional[Any] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 dataset_config: Optional[Dict[str, Any]] = None):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        # Streaming input pipeline: each dataset becomes per-rank
        # StreamShards the train fn pulls via
        # `train.get_dataset_shard(name).iter_batches()`; dataset_config
        # carries iter_batches defaults (batch_size, prefetch_batches...).
        self.datasets = datasets
        self.dataset_config = dataset_config
        if backend is not None:
            self.backend = backend

    def fit(self) -> Result:
        controller = TrainController(
            self.train_loop_per_worker,
            train_loop_config=self.train_loop_config,
            scaling_config=self.scaling_config,
            run_config=self.run_config,
            backend=self.backend,
            datasets=self.datasets,
            dataset_config=self.dataset_config)
        return controller.run()


class JaxTrainer(DataParallelTrainer):
    """Worker group wired through jax.distributed (ICI/DCN collectives)."""

    backend = "jax"


class CollectiveTrainer(DataParallelTrainer):
    """Worker group with a TCP collective group (CPU DDP; tests)."""

    backend = "collective"
