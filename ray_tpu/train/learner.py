"""Queue-driven learner loop: decouple experience production from updates.

Reference analog: ray.train's async data-ingest loops and the
learner-thread pattern of RLlib's async algorithms (IMPALA/APPO): a
producer (the RLHF rollout plane, a data pipeline, a replay buffer) pushes
batches into an EXTERNAL queue (`util/queue.py` — any worker in the
cluster can feed it) and a background loop drains it in FIFO order,
applying each batch through a caller-supplied callable (which typically
fans the batch out to a collective worker gang and allreduces gradients).

The loop is deliberately dumb: no retries, no reordering. FIFO application
is what makes sequence-number ledger proofs possible — the RLHF trainer
counter-proves "no experience lost or duplicated across a placement
switch" by comparing the set of seq_nos this loop consumed against the
set the rollout coordinator issued.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional

# Pushed by a producer to end the loop after everything queued ahead of it
# has been applied (a drain barrier, not an abort).
STOP = "__learner_stop__"


class QueueLearnerLoop:
    """Drains an experience queue on a background thread, FIFO.

    `apply_fn(batch)` runs on the loop thread for every non-STOP item; an
    exception stops the loop and is re-raised from `stop()`/`wait_for()`.
    """

    def __init__(self, queue, apply_fn: Callable[[Any], Any], *,
                 poll_interval: float = 0.02):
        self._queue = queue
        self._apply = apply_fn
        self._poll = poll_interval
        self._thread: Optional[threading.Thread] = None
        self._stop_seen = threading.Event()
        self._abort = threading.Event()
        self._lock = threading.Lock()
        self.updates_applied = 0
        self.last_error: Optional[BaseException] = None

    def start(self) -> "QueueLearnerLoop":
        if self._thread is not None:
            raise RuntimeError("learner loop already started")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="learner-loop")
        self._thread.start()
        return self

    def _run(self):
        while not self._abort.is_set():
            try:
                item = self._queue.get_nowait()
            except Exception:
                time.sleep(self._poll)
                continue
            if isinstance(item, str) and item == STOP:
                self._stop_seen.set()
                return
            try:
                self._apply(item)
            except BaseException as exc:  # surfaced via stop()/wait_for()
                self.last_error = exc
                self._stop_seen.set()
                return
            with self._lock:
                self.updates_applied += 1

    def wait_for(self, n_updates: int, timeout: float = 120.0) -> int:
        """Block until at least `n_updates` batches have been applied."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.last_error is not None:
                raise self.last_error
            with self._lock:
                if self.updates_applied >= n_updates:
                    return self.updates_applied
            time.sleep(self._poll)
        raise TimeoutError(
            f"learner loop applied {self.updates_applied}/{n_updates} "
            f"updates within {timeout}s")

    def stop(self, drain: bool = True, timeout: float = 60.0):
        """End the loop. drain=True pushes the STOP sentinel so every batch
        queued before it is applied first; drain=False aborts immediately
        (queued batches stay in the queue)."""
        if self._thread is None:
            return
        if drain:
            self._queue.put(STOP)
            if not self._stop_seen.wait(timeout):
                self._abort.set()
        else:
            self._abort.set()
        self._thread.join(timeout)
        self._thread = None
        if self.last_error is not None:
            raise self.last_error
