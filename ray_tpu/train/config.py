"""Train configuration objects.

Reference analog: python/ray/air/config.py (ScalingConfig:102, RunConfig,
CheckpointConfig, FailureConfig). TPU-native twist: workers are scaled by
TPU chips/slices, and the placement strategy defaults to STRICT_PACK so a
worker group lands on one ICI slice.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # TPU topology: request whole slices ("v5e-8") instead of loose chips.
    topology: Optional[str] = None

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 1.0
        if "CPU" not in res and not self.use_tpu:
            res["CPU"] = 1.0
        return res


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    failure_config: FailureConfig = dataclasses.field(default_factory=FailureConfig)
    # Result/lifecycle hooks (train/callbacks.py: Json/CSV/TensorBoard/
    # Wandb/Mlflow loggers, or user Callback subclasses).
    callbacks: Optional[list] = None

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.name or "train_run"
        return os.path.join(base, name)
