"""Per-run train telemetry: step phase breakdown, goodput, stragglers.

Reference analog: ray.train's v2 metrics surface plus the per-rank timing
attribution argued for by multi-tenant collective scheduling work (GADGET,
arxiv 2202.01158): aggregate throughput hides WHO is slow — a straggling
rank shows up in every OTHER rank's collective wait, so attribution needs
per-rank, per-phase seconds.

The flow: each worker's session accumulates named phase seconds
(`train.step_phase("data")`, the collective phase auto-wrapped by
`allreduce_gradients`) and closes a step record at every
`session.report()`. Records ride the existing results queue to the
controller, which folds them into one `TrainTelemetry` attached to
`Result.telemetry`:

  * goodput   — productive step seconds (rank 0) over run wall seconds,
                INCLUDING time lost to gang restarts and capacity waits
                (the denominator a TPU bill actually charges for).
  * stragglers — per-rank compute/collective seconds. In a synchronous
                ring, ranks finishing compute early burn the difference
                inside the collective — so the straggler is the rank with
                max compute and min collective wait.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

# Canonical phase keys of a step record (session._close_step): `total_s` is
# wall time since the previous report; `compute_s` is the unattributed
# residual after the named phases. `checkpoint_s` is the snapshot STALL the
# step paid; `checkpoint_persist_s` is background persist time that
# overlapped compute (booked separately so it never distorts the residual) —
# their ratio is the async checkpoint plane's win, per step.
# `input_wait_s` is time the step spent BLOCKED in next(batch) on a
# streaming dataset shard (data/streaming.py books it automatically) —
# near-zero means the pipelined data plane fully hid ingestion.
PHASE_KEYS = ("total_s", "data_s", "input_wait_s", "collective_s",
              "checkpoint_s", "checkpoint_persist_s", "compute_s", "other_s")


@dataclasses.dataclass
class TrainTelemetry:
    run_name: str
    steps: List[dict] = dataclasses.field(default_factory=list)
    per_rank: Dict[int, Dict[str, float]] = dataclasses.field(
        default_factory=dict)
    attempts: int = 1
    gang_restarts: int = 0
    wall_time_s: float = 0.0
    productive_time_s: float = 0.0
    # Hang-diagnosis events (TASK_STALLED / DEADLOCK_DETECTED) the
    # controller observed during this run.
    stall_events: int = 0

    def record_step(self, rec: dict) -> None:
        """Fold one per-rank step record (from `session.report()`) in.
        Rank 0's records define the per-step breakdown series and the
        productive-time numerator; every rank feeds the straggler table."""
        rank = int(rec.get("rank", 0))
        acc = self.per_rank.setdefault(
            rank, {**{k: 0.0 for k in PHASE_KEYS}, "steps": 0})
        for k in PHASE_KEYS:
            acc[k] += float(rec.get(k, 0.0))
        acc["steps"] += 1
        if rank == 0:
            self.steps.append(dict(rec))
            self.productive_time_s += float(rec.get("total_s", 0.0))

    @property
    def goodput(self) -> float:
        """Productive step time / run wall time, in [0, 1]. Wall time spans
        the whole `TrainController.run()` — worker placement, gang
        restarts, checkpoint restores, and capacity waits all dilute it."""
        if self.wall_time_s <= 0.0:
            return 0.0
        return min(1.0, self.productive_time_s / self.wall_time_s)

    def straggler_report(self) -> List[dict]:
        """Per-rank phase attribution, rank order. `straggler` marks the
        rank with the most compute seconds (the one the ring waits on)."""
        out = []
        for rank in sorted(self.per_rank):
            acc = self.per_rank[rank]
            out.append({"rank": rank, "steps": acc["steps"],
                        "compute_s": acc["compute_s"],
                        "collective_s": acc["collective_s"],
                        "data_s": acc["data_s"],
                        "input_wait_s": acc["input_wait_s"],
                        "checkpoint_s": acc["checkpoint_s"],
                        "checkpoint_persist_s": acc["checkpoint_persist_s"]})
        if out:
            slowest = max(out, key=lambda r: r["compute_s"])
            for r in out:
                r["straggler"] = r["rank"] == slowest["rank"]
        return out

    def to_dict(self) -> dict:
        return {"run_name": self.run_name, "steps": list(self.steps),
                "per_rank": {r: dict(a) for r, a in self.per_rank.items()},
                "attempts": self.attempts,
                "gang_restarts": self.gang_restarts,
                "wall_time_s": self.wall_time_s,
                "productive_time_s": self.productive_time_s,
                "goodput": self.goodput,
                "stragglers": self.straggler_report()}
