"""Train backends: how a worker group becomes a distributed compute group.

Reference analog: train/torch/config.py:36,153 (_TorchBackend wiring
init_process_group over NCCL) and backend_executor's rank/env plumbing
(:278-456). TPU-native:

  * JaxBackend — multi-host jax.distributed bootstrap (coordinator address
    rendezvoused through the GCS KV). After on_start, `jax.devices()` spans
    the whole worker group and pjit/shard_map programs run collectives over
    ICI/DCN. This is the FSDP/TP/SP path.
  * CollectiveBackend — out-of-graph gradient sync via the TCP communicator
    (gloo analog). This is the CPU-testable DDP path: each worker computes
    grads locally and allreduces host arrays.
"""

from __future__ import annotations

from typing import Dict, Optional


class Backend:
    backend_name = "base"

    def on_start(self, rank: int, world_size: int, group_name: str):
        """Runs INSIDE each train worker before the user function."""

    def on_shutdown(self, rank: int, world_size: int, group_name: str):
        pass


class JaxBackend(Backend):
    """jax.distributed across the worker group (the NCCL-process-group
    replacement). Workers must each own their TPU chips (TPU_VISIBLE_CHIPS
    is set by the raylet lease)."""

    backend_name = "jax"

    def on_start(self, rank: int, world_size: int, group_name: str):
        from ray_tpu.collective.collective import _gcs_kv
        from ray_tpu.collective.jax_backend import initialize_jax_distributed

        kv_put, kv_get = _gcs_kv()
        initialize_jax_distributed(rank, world_size, group_name, kv_put, kv_get)


class CollectiveBackend(Backend):
    """TCP collective group for out-of-graph DDP gradient sync."""

    backend_name = "collective"

    def __init__(self):
        self.comm = None

    def on_start(self, rank: int, world_size: int, group_name: str):
        from ray_tpu.collective.collective import init_collective_group

        global _active_group
        self.comm = init_collective_group(world_size, rank, backend="tcp",
                                          group_name=group_name)
        _active_group = group_name

    def on_shutdown(self, rank: int, world_size: int, group_name: str):
        from ray_tpu.collective.collective import destroy_collective_group

        try:
            destroy_collective_group(group_name)
        except Exception:
            pass
        self.comm = None


BACKENDS = {"jax": JaxBackend, "collective": CollectiveBackend, "none": Backend}

# The collective group name of the currently-running train job in this
# worker process (set by setup_backend; used by allreduce_gradients).
_active_group: Optional[str] = None


def make_backend(name_or_backend) -> Backend:
    if isinstance(name_or_backend, Backend):
        return name_or_backend
    return BACKENDS[name_or_backend or "none"]()


def allreduce_gradients(grads, group_name: Optional[str] = None):
    """DDP helper: mean-allreduce a pytree of host/jax arrays over the
    worker group's collective backend (reference: the NCCL allreduce inside
    DDP's backward). Use inside train loops running the CollectiveBackend."""
    import jax
    import numpy as np

    from ray_tpu.collective.collective import get_group

    comm = get_group(group_name or _active_group or "default")
    leaves, treedef = jax.tree.flatten(grads)
    flat = np.concatenate([np.asarray(l).ravel() for l in leaves]) \
        if leaves else np.zeros(0)
    reduced = comm.allreduce(flat, op="mean")
    out = []
    offset = 0
    for leaf in leaves:
        size = int(np.prod(np.asarray(leaf).shape)) if hasattr(leaf, "shape") else 1
        out.append(reduced[offset:offset + size].reshape(np.asarray(leaf).shape)
                   .astype(np.asarray(leaf).dtype))
        offset += size
    return jax.tree.unflatten(treedef, out)
