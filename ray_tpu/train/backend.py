"""Train backends: how a worker group becomes a distributed compute group.

Reference analog: train/torch/config.py:36,153 (_TorchBackend wiring
init_process_group over NCCL) and backend_executor's rank/env plumbing
(:278-456). TPU-native:

  * JaxBackend — multi-host jax.distributed bootstrap (coordinator address
    rendezvoused through the GCS KV). After on_start, `jax.devices()` spans
    the whole worker group and pjit/shard_map programs run collectives over
    ICI/DCN. This is the FSDP/TP/SP path.
  * CollectiveBackend — out-of-graph gradient sync via the TCP communicator
    (gloo analog). This is the CPU-testable DDP path: each worker computes
    grads locally and allreduces host arrays.
"""

from __future__ import annotations

from typing import Dict, Optional


class Backend:
    backend_name = "base"

    def on_start(self, rank: int, world_size: int, group_name: str):
        """Runs INSIDE each train worker before the user function."""

    def on_shutdown(self, rank: int, world_size: int, group_name: str):
        pass


class JaxBackend(Backend):
    """jax.distributed across the worker group (the NCCL-process-group
    replacement). Workers must each own their TPU chips (TPU_VISIBLE_CHIPS
    is set by the raylet lease)."""

    backend_name = "jax"

    def on_start(self, rank: int, world_size: int, group_name: str):
        from ray_tpu.collective.collective import _gcs_kv
        from ray_tpu.collective.jax_backend import initialize_jax_distributed

        kv_put, kv_get = _gcs_kv()
        initialize_jax_distributed(rank, world_size, group_name, kv_put, kv_get)


class CollectiveBackend(Backend):
    """TCP collective group for out-of-graph DDP gradient sync."""

    backend_name = "collective"

    def __init__(self):
        self.comm = None

    def on_start(self, rank: int, world_size: int, group_name: str):
        from ray_tpu.collective.collective import init_collective_group

        global _active_group
        self.comm = init_collective_group(world_size, rank, backend="tcp",
                                          group_name=group_name)
        _active_group = group_name

    def on_shutdown(self, rank: int, world_size: int, group_name: str):
        from ray_tpu.collective.collective import destroy_collective_group

        try:
            destroy_collective_group(group_name)
        except Exception:
            pass
        self.comm = None


BACKENDS = {"jax": JaxBackend, "collective": CollectiveBackend, "none": Backend}

# The collective group name of the currently-running train job in this
# worker process (set by setup_backend; used by allreduce_gradients).
_active_group: Optional[str] = None


def make_backend(name_or_backend) -> Backend:
    if isinstance(name_or_backend, Backend):
        return name_or_backend
    return BACKENDS[name_or_backend or "none"]()


def reduce_gradients(comm, grads, bucket_bytes: Optional[int] = None):
    """Bucketed overlapped mean-allreduce of a gradient pytree over `comm`.

    Reference analog: torch DDP's gradient-bucketing Reducer. Leaves are
    grouped by dtype (never concatenated across dtypes — a mixed f32/f64
    tree reduces each dtype natively instead of silently upcasting the
    whole buffer) and coalesced into flat buckets of ~`bucket_bytes`
    (cfg().ddp_bucket_bytes default). Each bucket's allreduce is launched
    asynchronously THE MOMENT the bucket fills, so the wire reduction of
    early buckets overlaps the flatten/copy work of later ones, and the
    per-group FIFO op thread pipelines the buckets back to back. Handles
    are then waited in launch order and leaves scattered back in their
    original tree positions and dtypes.
    """
    import jax
    import numpy as np

    from ray_tpu.config import cfg

    if bucket_bytes is None:
        bucket_bytes = cfg().ddp_bucket_bytes
    bucket_bytes = max(1, int(bucket_bytes))

    leaves, treedef = jax.tree.flatten(grads)
    arrs = [np.asarray(l) for l in leaves]
    out: list = [None] * len(leaves)

    # dtype -> list of (leaf index, flat view) accumulating the open bucket
    open_buckets: Dict[str, list] = {}
    open_bytes: Dict[str, int] = {}
    launched: list = []  # (Work, dtype, [(leaf idx, shape, size), ...])

    def _flush(dt: str):
        entries = open_buckets.pop(dt, None)
        open_bytes.pop(dt, None)
        if not entries:
            return
        flat = np.concatenate([v for _, v in entries]) if len(entries) > 1 \
            else np.ascontiguousarray(entries[0][1])
        meta = [(i, arrs[i].shape, arrs[i].size) for i, _ in entries]
        launched.append((comm.allreduce_async(flat, op="mean"), dt, meta))

    for i, a in enumerate(arrs):
        dt = a.dtype.str
        open_buckets.setdefault(dt, []).append((i, a.ravel()))
        open_bytes[dt] = open_bytes.get(dt, 0) + a.nbytes
        if open_bytes[dt] >= bucket_bytes:
            _flush(dt)
    for dt in list(open_buckets):
        _flush(dt)

    for work, dt, meta in launched:
        reduced = np.asarray(work.wait())
        if reduced.dtype.str != dt:  # integer mean comes back float64
            reduced = reduced.astype(np.dtype(dt))
        offset = 0
        for i, shape, size in meta:
            out[i] = reduced[offset:offset + size].reshape(shape)
            offset += size
    return jax.tree.unflatten(treedef, out)


def allreduce_gradients(grads, group_name: Optional[str] = None,
                        bucket_bytes: Optional[int] = None):
    """DDP helper: mean-allreduce a pytree of host/jax arrays over the
    worker group's collective backend (reference: the NCCL allreduce inside
    DDP's backward). Use inside train loops running the CollectiveBackend.
    Gradients are coalesced into per-dtype buckets whose ring allreduces
    launch as each bucket fills (see reduce_gradients). Inside a train
    worker the whole sync is booked to the step's "collective" phase
    (train/telemetry.py straggler attribution); outside one, the phase
    wrapper is a no-op."""
    from ray_tpu.collective.collective import get_group
    from ray_tpu.train.session import step_phase

    comm = get_group(group_name or _active_group or "default")
    with step_phase("collective"):
        return reduce_gradients(comm, grads, bucket_bytes=bucket_bytes)
