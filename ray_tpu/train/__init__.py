from ray_tpu.train.backend import allreduce_gradients  # noqa: F401
from ray_tpu.train.callbacks import (  # noqa: F401
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    MlflowLoggerCallback,
    TensorBoardLoggerCallback,
    WandbLoggerCallback,
)
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager  # noqa: F401
from ray_tpu.train.learner import QueueLearnerLoop  # noqa: F401
from ray_tpu.train.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.result import Result  # noqa: F401
from ray_tpu.train.session import (  # noqa: F401
    get_checkpoint,
    get_context,
    get_dataset_shard,
    load_state,
    report,
    step_phase,
)
from ray_tpu.train.telemetry import TrainTelemetry  # noqa: F401
from ray_tpu.train.trainer import (  # noqa: F401
    CollectiveTrainer,
    DataParallelTrainer,
    JaxTrainer,
)
