"""Checkpoints: directory handles + top-K retention + pytree (de)serialization.

Reference analog: python/ray/train/_checkpoint.py:56 (Checkpoint = filesystem
+ path), train/_internal/checkpoint_manager.py (top-K by score). Pytree
save/load is backed by the checkpoint plane's path-based manifest format
(ray_tpu/checkpoint/ — zero-pickle, reshard-on-restore); `load_pytree`
still reads the retired flat-npz + pickled-treedef layout for checkpoints
written before the manifest format existed.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class Checkpoint:
    """A handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @staticmethod
    def from_directory(path: str) -> "Checkpoint":
        return Checkpoint(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: str) -> str:
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"

    # -- pytree helpers ----------------------------------------------------

    @staticmethod
    def save_pytree(tree: Any, path: str, name: str = "state") -> "Checkpoint":
        """Synchronously save `tree` in the manifest format (a 1-shard
        checkpoint — the whole tree in one npz plus a path-based JSON
        leaf table; no pickled treedef)."""
        from ray_tpu.checkpoint import save_sharded

        save_sharded(tree, path, name=name, rank=0, world=1)
        return Checkpoint(path)

    def load_pytree(self, name: str = "state", template: Any = None) -> Any:
        """Load a pytree saved under this checkpoint. Reads the manifest
        format (any shard count — reassembles global leaves); falls back
        to the legacy `{name}.npz` + `{name}.treedef.pkl` layout for old
        checkpoints. `template` restores trees with custom container
        nodes (optax states etc.) into their original structure."""
        from ray_tpu.checkpoint import has_manifest, restore_tree

        if has_manifest(self.path, name):
            return restore_tree(self.path, name=name, template=template)
        legacy = os.path.join(self.path, f"{name}.treedef.pkl")
        if not os.path.exists(legacy):
            from ray_tpu.checkpoint import CheckpointNotCommitted

            raise CheckpointNotCommitted(
                f"no {name!r} checkpoint (manifest or legacy) under "
                f"{self.path!r}")
        import jax

        with open(legacy, "rb") as f:
            treedef = pickle.load(f)
        data = np.load(os.path.join(self.path, f"{name}.npz"))
        leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
        return jax.tree.unflatten(treedef, leaves)


class CheckpointManager:
    """Top-K checkpoint retention under a run directory."""

    def __init__(self, run_path: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.run_path = run_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries: List[Tuple[float, str, Dict]] = []
        self._counter = 0
        os.makedirs(run_path, exist_ok=True)

    def register(self, source_dir: str, metrics: Dict) -> Checkpoint:
        self._counter += 1
        dest = os.path.join(self.run_path, f"checkpoint_{self._counter:06d}")
        if os.path.abspath(source_dir) != dest:
            shutil.copytree(source_dir, dest, dirs_exist_ok=True)
        with open(os.path.join(dest, "metrics.json"), "w") as f:
            json.dump({k: v for k, v in metrics.items()
                       if isinstance(v, (int, float, str, bool))}, f)
        score = float(metrics.get(self.score_attribute, self._counter)) \
            if self.score_attribute else float(self._counter)
        self._entries.append((score, dest, dict(metrics)))
        self._prune()
        return Checkpoint(dest)

    def _prune(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        reverse = self.score_order == "max"
        ranked = sorted(self._entries, key=lambda e: e[0], reverse=reverse)
        keep = ranked[:self.num_to_keep]
        # The most recent checkpoint is never pruned, even when it scores
        # worst: `latest_checkpoint` feeds the drain / gang-restart resume
        # paths, which must not point at a deleted directory.
        latest = self._entries[-1]
        if latest not in keep:
            if keep:
                keep[-1] = latest
            else:
                keep = [latest]
        for entry in self._entries:
            if entry not in keep:
                shutil.rmtree(entry[1], ignore_errors=True)
        self._entries = [e for e in self._entries if e in keep]

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        reverse = self.score_order == "max"
        best = sorted(self._entries, key=lambda e: e[0], reverse=reverse)[0]
        return Checkpoint(best[1])

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint(self._entries[-1][1])
