"""Checkpoints: directory handles + top-K retention + pytree (de)serialization.

Reference analog: python/ray/train/_checkpoint.py:56 (Checkpoint = filesystem
+ path), train/_internal/checkpoint_manager.py (top-K by score). Pytree
save/load uses a flat npz + pickled treedef — works for jax arrays on any
mesh (arrays are fetched to host; sharded restore re-shards via device_put).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class Checkpoint:
    """A handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @staticmethod
    def from_directory(path: str) -> "Checkpoint":
        return Checkpoint(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: str) -> str:
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"

    # -- pytree helpers ----------------------------------------------------

    @staticmethod
    def save_pytree(tree: Any, path: str, name: str = "state") -> "Checkpoint":
        import jax

        os.makedirs(path, exist_ok=True)
        leaves, treedef = jax.tree.flatten(tree)
        host_leaves = [np.asarray(leaf) for leaf in leaves]
        np.savez(os.path.join(path, f"{name}.npz"),
                 **{f"leaf_{i}": l for i, l in enumerate(host_leaves)})
        with open(os.path.join(path, f"{name}.treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)
        return Checkpoint(path)

    def load_pytree(self, name: str = "state") -> Any:
        import jax

        with open(os.path.join(self.path, f"{name}.treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        data = np.load(os.path.join(self.path, f"{name}.npz"))
        leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
        return jax.tree.unflatten(treedef, leaves)


class CheckpointManager:
    """Top-K checkpoint retention under a run directory."""

    def __init__(self, run_path: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.run_path = run_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries: List[Tuple[float, str, Dict]] = []
        self._counter = 0
        os.makedirs(run_path, exist_ok=True)

    def register(self, source_dir: str, metrics: Dict) -> Checkpoint:
        self._counter += 1
        dest = os.path.join(self.run_path, f"checkpoint_{self._counter:06d}")
        if os.path.abspath(source_dir) != dest:
            shutil.copytree(source_dir, dest, dirs_exist_ok=True)
        with open(os.path.join(dest, "metrics.json"), "w") as f:
            json.dump({k: v for k, v in metrics.items()
                       if isinstance(v, (int, float, str, bool))}, f)
        score = float(metrics.get(self.score_attribute, self._counter)) \
            if self.score_attribute else float(self._counter)
        self._entries.append((score, dest, dict(metrics)))
        self._prune()
        return Checkpoint(dest)

    def _prune(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        reverse = self.score_order == "max"
        ranked = sorted(self._entries, key=lambda e: e[0], reverse=reverse)
        keep = ranked[:self.num_to_keep]
        for score, path, metrics in self._entries:
            if (score, path, metrics) not in keep:
                shutil.rmtree(path, ignore_errors=True)
        self._entries = [e for e in self._entries if e in keep]

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        reverse = self.score_order == "max"
        best = sorted(self._entries, key=lambda e: e[0], reverse=reverse)[0]
        return Checkpoint(best[1])

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint(self._entries[-1][1])
