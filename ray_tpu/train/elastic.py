"""Elastic training policies: scaling + failure handling.

Reference analog: python/ray/train/v2/_internal/execution/scaling_policy/
and failure_handling/. The controller consults the ScalingPolicy for the
world size before every worker-group (re)start and periodically during
training; a resize is a controlled restart — workers checkpoint, the group
is rebuilt at the new size, and training resumes from the latest checkpoint
(resharding is the train_fn's responsibility via its backend/mesh, which it
rebuilds from the restored state at the new world size).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ray_tpu.train.config import ScalingConfig


@dataclasses.dataclass
class ScalingDecision:
    kind: str              # "noop" | "resize"
    num_workers: int = 0


class ScalingPolicy:
    """Decides the worker-group world size from cluster state."""

    def initial_workers(self, scaling: ScalingConfig,
                        available: Dict[str, float]) -> int:
        return scaling.num_workers

    def on_failure(self, scaling: ScalingConfig, current: int,
                   available: Dict[str, float]) -> ScalingDecision:
        """Called before a failure restart: may shrink the group to what the
        (possibly degraded) cluster can still place."""
        return ScalingDecision("resize", current)

    def periodic(self, scaling: ScalingConfig, current: int,
                 available: Dict[str, float]) -> ScalingDecision:
        """Called every train_elastic_check_interval_s during training."""
        return ScalingDecision("noop")


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured size (the default, v1-compatible behavior)."""


class ElasticScalingPolicy(ScalingPolicy):
    """Scale the group within [min_workers, max_workers] to the resources
    actually available: shrink instead of failing when nodes die, grow when
    capacity returns (TPU deployments: slice granularity comes from
    resources_per_worker requesting whole slices)."""

    def __init__(self, min_workers: int, max_workers: int):
        assert 1 <= min_workers <= max_workers
        self.min_workers = min_workers
        self.max_workers = max_workers

    def _fit(self, scaling: ScalingConfig,
             available: Dict[str, float]) -> int:
        per = scaling.worker_resources()
        n = self.max_workers
        for res, need in per.items():
            if need > 0:
                n = min(n, int(available.get(res, 0.0) // need))
        return max(self.min_workers, min(self.max_workers, n))

    def initial_workers(self, scaling, available) -> int:
        return self._fit(scaling, available)

    def on_failure(self, scaling, current, available) -> ScalingDecision:
        return ScalingDecision("resize", self._fit(scaling, available))

    def periodic(self, scaling, current, available) -> ScalingDecision:
        fit = self._fit(scaling, available)
        # Growing is worth a restart; shrinking below current only happens
        # via failure (a healthy group keeps its reserved resources).
        if fit > current:
            return ScalingDecision("resize", fit)
        return ScalingDecision("noop")


# Error-string markers of gang failures: the whole worker group is broken
# as a unit (a TPU slice died, or a collective aborted under it) — restart
# everything from the latest checkpoint rather than probing individual
# workers. Workers report exceptions as strings, so markers are textual.
GANG_FAILURE_MARKERS = (
    "TpuSliceLost",
    "TpuSliceLostError",
    "CollectiveAbortError",
)


def is_gang_failure(error: Optional[str]) -> bool:
    """True when `error` (a worker/controller error string) indicates a
    slice loss or collective abort — i.e. the group must be gang-restarted."""
    if not error:
        return False
    return any(marker in error for marker in GANG_FAILURE_MARKERS)


class FailureDecision:
    RETRY = "retry"
    FAIL = "fail"


class FailurePolicy:
    """Decides what to do when the worker group fails.
    Reference analog: v2 failure_handling/failure_policy.py."""

    def __init__(self, max_failures: int = 0):
        self.max_failures = max_failures
        self.failures = 0

    def decide(self, error: str) -> str:
        self.failures += 1
        if self.max_failures < 0:  # infinite retries
            return FailureDecision.RETRY
        if self.failures <= self.max_failures:
            return FailureDecision.RETRY
        return FailureDecision.FAIL
