"""Runtime environments: per-task/actor working_dir, py_modules, env_vars.

Reference analog: python/ray/_private/runtime_env/ (working_dir.py, py_modules,
plugin.py; URI-cached materialization by the per-node agent, raylet <->
agent HTTP in src/ray/raylet/runtime_env_agent_client.cc). The TPU build
materializes in-process in the worker at task-dispatch time: packages are
content-addressed zips in the GCS KV, extracted once per node into
``<session>/runtime_resources/<hash>/`` and prepended to sys.path.

pip/conda/uv envs: the reference materializes networked environments; this
build targets air-gapped TPU pods, so ``pip`` specs are validated against
already-importable distributions and otherwise raise (gate:
RAY_TPU_ALLOW_MISSING_PIP=1 downgrades to a warning).
"""

from __future__ import annotations

import hashlib
import io
import logging
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

PKG_PREFIX = b"pkg:"
_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 512 << 20


class RuntimeEnv(dict):
    """Validated runtime environment spec (a plain dict underneath so it
    pickles into TaskSpec cheaply)."""

    KEYS = {"working_dir", "py_modules", "env_vars", "pip", "config"}

    def __init__(self, *, working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 env_vars: Optional[Dict[str, str]] = None,
                 pip: Optional[List[str]] = None,
                 config: Optional[dict] = None):
        super().__init__()
        if working_dir is not None:
            self["working_dir"] = working_dir
        if py_modules:
            self["py_modules"] = list(py_modules)
        if env_vars:
            bad = {k: v for k, v in env_vars.items()
                   if not isinstance(k, str) or not isinstance(v, str)}
            if bad:
                raise TypeError(f"env_vars must be str->str, got {bad}")
            self["env_vars"] = dict(env_vars)
        if pip:
            self["pip"] = list(pip)
        if config:
            self["config"] = dict(config)


def zip_directory(path: str) -> bytes:
    """Deterministic zip of a directory tree (sorted entries, zeroed mtimes)
    so equal trees produce equal content hashes."""
    out = io.BytesIO()
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as zf:
        entries = []
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for fname in sorted(files):
                full = os.path.join(root, fname)
                entries.append((os.path.relpath(full, path), full))
        for rel, full in entries:
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            with open(full, "rb") as f:
                zf.writestr(info, f.read())
    data = out.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(f"runtime_env package too large: {len(data)} bytes")
    return data


# path -> (tree signature, uri): avoids re-zip + re-upload of an unchanged
# directory on every task submission.
_upload_cache: Dict[str, tuple] = {}


def _tree_signature(path: str) -> str:
    """Cheap change detector: relative paths + sizes + mtimes."""
    parts = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for fname in sorted(files):
            full = os.path.join(root, fname)
            try:
                st = os.stat(full)
            except OSError:
                continue
            parts.append(f"{os.path.relpath(full, path)}:{st.st_size}:"
                         f"{st.st_mtime_ns}")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()


def upload_package(core, path: str) -> str:
    """Zip + content-address + upload a directory; returns its pkg URI.
    Unchanged trees (by path+size+mtime signature) skip both zip and RPC."""
    path = os.path.abspath(path)
    sig = _tree_signature(path)
    cached = _upload_cache.get(path)
    if cached is not None and cached[0] == sig:
        return cached[1]
    data = zip_directory(path)
    digest = hashlib.sha1(data).hexdigest()
    uri = f"kv://pkg/{digest}"
    core.io.run(core.gcs.call("kv_put", key=PKG_PREFIX + digest.encode(),
                              value=data, overwrite=False))
    _upload_cache[path] = (sig, uri)
    return uri


def prepare_runtime_env(core, env: Optional[dict]) -> Optional[dict]:
    """Driver-side: each plugin resolves its key (local paths -> uploaded
    pkg URIs; runs at submit time, once per distinct directory)."""
    if not env:
        return env
    from ray_tpu.runtime_envs.plugin import plugins_for

    env = dict(env)
    for plugin in plugins_for(env):
        env[plugin.name] = plugin.resolve(core, env[plugin.name])
    return env


def _fetch_and_extract(core, uri: str, session_dir: str) -> str:
    digest = uri.rsplit("/", 1)[-1]
    dest = os.path.join(session_dir, "runtime_resources", digest)
    if os.path.isdir(dest):
        return dest  # URI cache hit
    reply = core.io.run(core.gcs.call("kv_get", key=PKG_PREFIX + digest.encode()))
    blob = reply.get("value")
    if blob is None:
        raise RuntimeError(f"runtime_env package {uri} not found in GCS")
    tmp = f"{dest}.{os.getpid()}.tmp"
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.replace(tmp, dest)
    except OSError:
        # Concurrent extractor won; use theirs.
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return dest


class AppliedEnv:
    """Worker-side record of one applied env, so it can be rolled back after
    the task (env_vars) while extracted packages stay cached."""

    def __init__(self):
        self.saved_env: Dict[str, Optional[str]] = {}
        self.added_paths: List[str] = []
        self.prev_cwd: Optional[str] = None
        self.held_uris: List[str] = []

    def undo(self):
        for key, old in self.saved_env.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old
        for p in self.added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if self.prev_cwd is not None:
            try:
                os.chdir(self.prev_cwd)
            except OSError:
                pass


def build_env_context(core, env: Optional[dict], session_dir: str):
    """Run every plugin's create() for this env into one RuntimeEnvContext
    (no process mutation yet). The agent/worker applies the context."""
    from ray_tpu.runtime_envs.plugin import RuntimeEnvContext, plugins_for

    ctx = RuntimeEnvContext()
    if not env:
        return ctx
    ctx._env_config = env.get("config") or {}  # plugin-visible knobs
    for plugin in plugins_for(env):
        plugin.create(core, env[plugin.name], ctx, session_dir)
    return ctx


def apply_runtime_env(core, env: Optional[dict], session_dir: str) -> AppliedEnv:
    """Worker-side: materialize (via the plugin registry) and activate a
    runtime env for a task.

    Fail-safe ordering: plugin create() runs fully — including validations
    that can reject the env (pip check mode) — before any process
    mutation, and a failure mid-application rolls back whatever was
    already applied: a rejected env must not contaminate the worker for
    later tasks."""
    applied = AppliedEnv()
    if not env:
        return applied
    ctx = build_env_context(core, env, session_dir)
    try:
        for key, value in ctx.env_vars.items():
            applied.saved_env[key] = os.environ.get(key)
            os.environ[key] = value
        for path in ctx.py_paths:
            if path not in sys.path:
                sys.path.insert(0, path)
                applied.added_paths.append(path)
        if ctx.cwd:
            applied.prev_cwd = os.getcwd()
            os.chdir(ctx.cwd)
        # Node-level refcounting: tell the raylet's env agent which URIs
        # this worker now pins (release happens on worker exit or env
        # switch — see raylet EnvAgent).
        if ctx.uris:
            applied.held_uris = list(ctx.uris)
            _notify_agent_hold(core, ctx.uris)
    except BaseException:
        applied.undo()
        raise
    return applied


def _notify_agent_hold(core, uris: List[str]):
    """Register URI holds with this node's raylet env agent.

    AWAITED (short timeout), not fire-and-forget: until the pin is
    acknowledged, another worker's release could push the cache over
    budget and evict the very directory this worker is about to import
    from. A timeout degrades to unpinned-but-materialized (the pre-agent
    behavior) rather than failing the task."""
    try:
        if getattr(core, "raylet", None) is None:
            return
        worker = getattr(core, "worker_ident", "") or ""
        # release_others: switching envs on a reused worker must drop pins
        # for URIs the worker no longer runs, or eviction starves.
        core.io.run(core.raylet.call(
            "env_hold", uris=list(uris), worker=worker,
            release_others=True), timeout=10)
    except Exception:
        logger.warning("env_hold registration failed; env URIs unpinned",
                       exc_info=True)
