"""Ray-on-Spark: bootstrap a ray_tpu cluster on a Spark cluster.

Reference analog: python/ray/util/spark/cluster_init.py
(setup_ray_cluster / shutdown_ray_cluster / MAX_NUM_WORKER_NODES). Shape
matches the reference's design:

  * the HEAD (GCS + a 0-CPU raylet) runs next to the Spark driver — no
    tasks schedule onto the driver host by default;
  * each ray_tpu WORKER node is pinned to one Spark executor by a
    long-running BARRIER job (barrier so Spark co-schedules every worker
    and tears them down together), launched from a background thread;
  * worker nodes self-terminate when the head's GCS becomes unreachable,
    so a driver-side shutdown (or driver death) reaps the whole cluster
    even if Spark's task-cancel signal is lost.

pyspark is NOT required to import this module: `setup_ray_cluster`
accepts any object with the SparkSession surface it uses
(sparkContext.parallelize(...).barrier().mapPartitions(...).collect(),
setJobGroup/cancelJobGroup, defaultParallelism) — the tests drive it
with an in-process fake the same way the KubeRay provider is driven by
FakeKubeApi; a real SparkSession works unchanged.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

# Sentinel: size the cluster to the Spark cluster's default parallelism
# (reference: ray.util.spark.MAX_NUM_WORKER_NODES).
MAX_NUM_WORKER_NODES = -1

_active_cluster: Optional["RayClusterOnSpark"] = None


def _run_worker_node(gcs_address: str, resources: Dict[str, float],
                     object_store_memory: int, auth_token_hex: str,
                     poll_interval_s: float = 2.0) -> str:
    """Runs ON A SPARK EXECUTOR (inside the barrier task): start one
    ray_tpu worker node attached to `gcs_address` and babysit it until
    the head disappears. Returns the node id hex on exit.

    The babysit loop is the cleanup guarantee: Spark task-kill runs the
    finally (normal cancel), and if the executor is lost abruptly the
    next GCS health sweep marks the node dead — while a lost HEAD makes
    this loop kill its raylet, so no orphan raylets outlive the cluster
    (reference: start_ray_node's parent-death watch, cluster_init.py)."""
    import socket
    import tempfile

    from ray_tpu.runtime import node as node_mod

    if auth_token_hex:
        os.environ["RAY_TPU_AUTH_TOKEN"] = auth_token_hex
    host, port = gcs_address.rsplit(":", 1)
    session_dir = tempfile.mkdtemp(prefix="ray_tpu_spark_worker_")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    import sys

    worker_env = {"PYTHONPATH": ":".join(p for p in sys.path if p)}
    if auth_token_hex:
        worker_env["RAY_TPU_AUTH_TOKEN"] = auth_token_hex
    proc, info = node_mod.start_raylet(
        session_dir, (host, int(port)), dict(resources), {},
        object_store_memory, is_head=False, worker_env=worker_env,
        name=f"spark-worker-{uuid.uuid4().hex[:6]}")
    try:
        while proc.poll() is None:
            time.sleep(poll_interval_s)
            try:
                with socket.create_connection((host, int(port)),
                                              timeout=5):
                    pass
            except OSError:
                # Head gone: the cluster is over; don't orphan the raylet.
                break
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
    return info["node_id"]


class RayClusterOnSpark:
    """Handle for a ray_tpu cluster running on Spark executors."""

    def __init__(self, spark, address: str, session_dir: str, gcs_proc,
                 head_proc, job_group: str, job_thread: threading.Thread,
                 num_workers: int):
        self.spark = spark
        self.address = address
        self.session_dir = session_dir
        self._gcs_proc = gcs_proc
        self._head_proc = head_proc
        self._job_group = job_group
        self._job_thread = job_thread
        self.num_workers = num_workers
        self._down = False

    def shutdown(self):
        global _active_cluster
        if self._down:
            return
        self._down = True
        try:
            self.spark.sparkContext.cancelJobGroup(self._job_group)
        except Exception:
            logger.warning("cancelJobGroup failed", exc_info=True)
        # Killing the head makes every worker's babysit loop exit even if
        # the Spark cancel never reaches an executor.
        for proc in (self._head_proc, self._gcs_proc):
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        self._job_thread.join(timeout=30)
        if _active_cluster is self:
            _active_cluster = None


def setup_ray_cluster(
        *, spark, max_worker_nodes: int,
        num_cpus_worker_node: int = 1,
        num_tpus_worker_node: int = 0,
        resources_worker_node: Optional[Dict[str, float]] = None,
        object_store_memory_worker_node: int = 256 << 20,
        head_resources: Optional[Dict[str, float]] = None,
        timeout_s: float = 120.0,
) -> Tuple[str, RayClusterOnSpark]:
    """Start a ray_tpu cluster across a Spark cluster's executors.

    Returns (address, handle); connect with
    ``ray_tpu.init(address=address)``, tear down with
    ``shutdown_ray_cluster()`` (or ``handle.shutdown()``).
    """
    global _active_cluster
    if _active_cluster is not None:
        raise RuntimeError(
            "a ray_tpu cluster is already running on this Spark session; "
            "call shutdown_ray_cluster() first")
    from ray_tpu.runtime import node as node_mod
    from ray_tpu.runtime.rpc import get_session_token

    sc = spark.sparkContext
    n = max_worker_nodes
    if n == MAX_NUM_WORKER_NODES:
        n = int(getattr(sc, "defaultParallelism", 2))
    if n <= 0:
        raise ValueError(f"max_worker_nodes must be positive or "
                         f"MAX_NUM_WORKER_NODES, got {max_worker_nodes}")

    session_dir = node_mod.new_session_dir()
    gcs_proc, gcs_address = node_mod.start_gcs(session_dir)
    try:
        # 0-CPU head: keeps GCS-adjacent services local while scheduling
        # no work onto the Spark driver host (reference default).
        import sys

        head_env = {"PYTHONPATH": ":".join(p for p in sys.path if p)}
        head_proc, _head_info = node_mod.start_raylet(
            session_dir, gcs_address, dict(head_resources or {"CPU": 0.0}),
            {"spark-role": "head"}, 128 << 20, is_head=True,
            worker_env=head_env, name="spark-head")
    except Exception:
        # Don't orphan the GCS (it would squat its port for the next
        # setup attempt on this host).
        gcs_proc.terminate()
        raise
    address = f"{gcs_address[0]}:{gcs_address[1]}"
    token = get_session_token()
    token_hex = token.hex() if token else ""

    res: Dict[str, float] = {"CPU": float(num_cpus_worker_node)}
    if num_tpus_worker_node:
        res["TPU"] = float(num_tpus_worker_node)
    res.update({k: float(v)
                for k, v in (resources_worker_node or {}).items()})

    job_group = f"ray-tpu-on-spark-{uuid.uuid4().hex[:8]}"

    def _barrier_job():
        try:
            sc.setJobGroup(job_group,
                           "ray_tpu worker nodes (long-running)")
            (sc.parallelize(range(n), n)
             .barrier()
             .mapPartitions(lambda _it: [_run_worker_node(
                 address, res, object_store_memory_worker_node,
                 token_hex)])
             .collect())
        except Exception:
            logger.info("ray-on-spark barrier job ended", exc_info=True)

    job_thread = threading.Thread(target=_barrier_job, daemon=True,
                                  name=job_group)
    job_thread.start()

    handle = RayClusterOnSpark(spark, address, session_dir, gcs_proc,
                               head_proc, job_group, job_thread, n)
    # Wait for all n workers to register with the GCS.
    deadline = time.monotonic() + timeout_s
    while True:
        alive = _alive_worker_count(session_dir, gcs_address)
        if alive >= n:
            break
        if time.monotonic() > deadline:
            handle.shutdown()
            raise TimeoutError(
                f"only {alive}/{n} ray_tpu worker nodes registered within "
                f"{timeout_s}s")
        time.sleep(0.5)
    _active_cluster = handle
    return address, handle


def _alive_worker_count(session_dir: str, gcs_address) -> int:
    """Count alive non-head nodes via a short-lived GCS client."""
    import asyncio

    from ray_tpu.runtime.rpc import RpcClient

    async def _count():
        client = RpcClient(*gcs_address)
        await client.connect(timeout=10)
        try:
            nodes = await client.call("get_nodes")
        finally:
            await client.close()
        return sum(1 for nd in nodes
                   if nd.get("alive") and not nd.get("is_head"))

    try:
        return asyncio.run(_count())
    except Exception:
        return 0


def shutdown_ray_cluster():
    """Tear down the cluster started by setup_ray_cluster."""
    global _active_cluster
    if _active_cluster is None:
        raise RuntimeError("no ray_tpu cluster is running on Spark")
    _active_cluster.shutdown()
