"""Distributed FIFO queue backed by a detached actor.

Reference analog: python/ray/util/queue.py (Queue wrapping an _QueueActor).
The TPU build keeps the same shape: a plain asyncio-free actor holds a
collections.deque; Queue methods are thin RPCs against it, so any worker in
the cluster can share one queue by name.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        from collections import deque

        self._maxsize = maxsize
        self._items = deque()

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return self._maxsize > 0 and len(self._items) >= self._maxsize

    def put(self, item) -> bool:
        if self.full():
            return False
        self._items.append(item)
        return True

    def put_batch(self, items) -> int:
        n = 0
        for item in items:
            if not self.put(item):
                break
            n += 1
        return n

    def get(self):
        if not self._items:
            return False, None
        return True, self._items.popleft()

    def get_batch(self, n: int):
        out = []
        while self._items and len(out) < n:
            out.append(self._items.popleft())
        return out


class Queue:
    """FIFO queue usable from any driver/worker/actor in the cluster."""

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        cls = ray_tpu.remote(_QueueActor)
        self._actor = cls.options(**opts).remote(maxsize)
        self._maxsize = maxsize

    def __reduce__(self):
        return (_rebuild_queue, (self._actor, self._maxsize))

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self._actor.full.remote())

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item)):
                return
            if not block:
                raise Full()
            if deadline is not None and time.monotonic() >= deadline:
                raise Full()
            time.sleep(0.01)

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]):
        n = ray_tpu.get(self._actor.put_batch.remote(list(items)))
        if n < len(items):
            raise Full(f"queue accepted only {n}/{len(items)} items")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self._actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty()
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty()
            time.sleep(0.01)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return ray_tpu.get(self._actor.get_batch.remote(num_items))

    def shutdown(self, force: bool = True):
        ray_tpu.kill(self._actor, no_restart=True)


def _rebuild_queue(actor, maxsize):
    q = Queue.__new__(Queue)
    q._actor = actor
    q._maxsize = maxsize
    return q
