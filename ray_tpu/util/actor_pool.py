"""ActorPool: load-balance a stream of method calls over a fixed actor set.

Reference analog: python/ray/util/actor_pool.py (same public surface:
map / map_unordered / submit / get_next / get_next_unordered / push / pop_idle).

Bookkeeping model: each submit is numbered by a monotone sequence. A call is
either *in flight* (`_inflight`: ref -> (seq, actor-or-None)) or *backlogged*
(`_backlog`) waiting for a free actor. Finished-but-unretrieved results keep
their entry in `_inflight` with the actor slot already recycled (None), so
ordered retrieval never blocks actor reuse.
"""

from __future__ import annotations

from collections import deque
from typing import (Any, Callable, Deque, Dict, Iterable, Iterator, List,
                    Optional, Tuple, TypeVar)

import ray_tpu

V = TypeVar("V")


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._free_actors: Deque[Any] = deque(actors)
        # ref -> (submit seq, actor). actor becomes None once recycled
        # (task finished, result not yet retrieved).
        self._inflight: Dict[Any, Tuple[int, Any]] = {}
        self._result_refs: Dict[int, Any] = {}   # submit seq -> ref
        self._submit_seq = 0
        self._return_seq = 0
        self._backlog: Deque[tuple] = deque()

    # -- submission ---------------------------------------------------------
    def submit(self, fn: Callable[[Any, V], Any], value: V):
        """fn(actor, value) must return an ObjectRef (call a .remote method)."""
        if not self._free_actors:
            self._backlog.append((fn, value))
            return
        actor = self._free_actors.popleft()
        ref = fn(actor, value)
        self._inflight[ref] = (self._submit_seq, actor)
        self._result_refs[self._submit_seq] = ref
        self._submit_seq += 1

    def map(self, fn: Callable[[Any, V], Any],
            values: Iterable[V]) -> Iterator[Any]:
        """Ordered map over values; yields results as they become ready in order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, V], Any],
                      values: Iterable[V]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- retrieval ----------------------------------------------------------
    def has_next(self) -> bool:
        return bool(self._result_refs) or bool(self._backlog)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in submission order. A timeout leaves the pool state
        untouched; a task error is raised only after its actor is recycled."""
        if not self.has_next():
            raise StopIteration("no more results")
        seq = self._return_seq
        # The ref for seq may not exist yet while its submit sits in the
        # backlog; free an actor at a time until it gets dispatched.
        while seq not in self._result_refs:
            self._recycle_one(timeout)
        ref = self._result_refs[seq]
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        del self._result_refs[seq]
        self._return_seq += 1
        _, actor = self._inflight.pop(ref)
        if actor is not None:
            self._release(actor)
        return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no more results")
        while not self._inflight:
            self._recycle_one(timeout)
        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        ref = ready[0]
        seq, actor = self._inflight.pop(ref)
        del self._result_refs[seq]
        if actor is not None:
            self._release(actor)
        return ray_tpu.get(ref)

    # -- actor lifecycle ----------------------------------------------------
    def _release(self, actor):
        """Return an actor to the free set, immediately dispatching the
        oldest backlogged submit onto it if one is waiting."""
        self._free_actors.append(actor)
        if self._backlog:
            self.submit(*self._backlog.popleft())

    def _recycle_one(self, timeout: Optional[float]):
        """Block until any still-running call finishes and free its actor,
        keeping the result ref around for ordered retrieval."""
        running = [ref for ref, (_, a) in self._inflight.items()
                   if a is not None]
        if not running:
            raise RuntimeError("pool has pending submits but no running tasks")
        ready, _ = ray_tpu.wait(running, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for an actor to free up")
        ref = ready[0]
        seq, actor = self._inflight[ref]
        self._inflight[ref] = (seq, None)
        self._release(actor)

    def push(self, actor: Any):
        """Add a new idle actor to the pool."""
        busy = {a for _, a in self._inflight.values()}
        if actor in self._free_actors or actor in busy:
            raise ValueError("actor already in pool")
        self._release(actor)

    def pop_idle(self) -> Optional[Any]:
        if self._free_actors:
            return self._free_actors.pop()
        return None

    def has_free(self) -> bool:
        return bool(self._free_actors) and not self._backlog
