"""ActorPool: load-balance a stream of method calls over a fixed actor set.

Reference analog: python/ray/util/actor_pool.py (same public surface:
map / map_unordered / submit / get_next / get_next_unordered / push / pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, TypeVar

import ray_tpu

V = TypeVar("V")


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle_actors: List[Any] = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    def map(self, fn: Callable[[Any, V], Any], values: Iterable[V]) -> Iterator[Any]:
        """Ordered map over values; yields results as they become ready in order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, V], Any],
                      values: Iterable[V]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable[[Any, V], Any], value: V):
        """fn(actor, value) must return an ObjectRef (call a .remote method)."""
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def _return_actor(self, actor):
        self._idle_actors.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in submission order. A timeout leaves the pool state
        untouched; a task error is raised only after its actor is recycled."""
        if not self.has_next():
            raise StopIteration("no more results")
        idx = self._next_return_index
        # The future for idx may not exist yet if its submit is still pending.
        while idx not in self._index_to_future:
            self._drain_one(timeout)
        future = self._index_to_future[idx]
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        del self._index_to_future[idx]
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(future)
        if actor is not None:
            self._return_actor(actor)
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no more results")
        while not self._future_to_actor:
            self._drain_one(timeout)
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        idx, actor = self._future_to_actor.pop(future)
        del self._index_to_future[idx]
        if actor is not None:
            self._return_actor(actor)
        return ray_tpu.get(future)

    def _drain_one(self, timeout: Optional[float]):
        """Wait for any still-running task to finish and recycle its actor,
        keeping its result future around for ordered retrieval."""
        running = [f for f, (_, a) in self._future_to_actor.items()
                   if a is not None]
        if not running:
            raise RuntimeError("pool has pending submits but no running tasks")
        ready, _ = ray_tpu.wait(running, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for an actor to free up")
        future = ready[0]
        idx, actor = self._future_to_actor[future]
        self._future_to_actor[future] = (idx, None)
        self._return_actor(actor)

    def push(self, actor: Any):
        """Add a new idle actor to the pool."""
        busy = {a for _, a in self._future_to_actor.values()}
        if actor in self._idle_actors or actor in busy:
            raise ValueError("actor already in pool")
        self._return_actor(actor)

    def pop_idle(self) -> Optional[Any]:
        if self._idle_actors:
            return self._idle_actors.pop()
        return None

    def has_free(self) -> bool:
        return bool(self._idle_actors) and not self._pending_submits
