from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.queue import Empty, Full, Queue  # noqa: F401
from ray_tpu.core.placement_group import (  # noqa: F401
    PACK,
    SPREAD,
    STRICT_PACK,
    STRICT_SPREAD,
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
